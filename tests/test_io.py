"""Data-feed IO tests, mirroring the reference's TestReader.java: split
offsets tile the byte range exactly (:42-60), multi-file read correctness
(:66+), and shuffle mode — plus native-vs-python parity and the jax feed."""

import os

import numpy as np
import pytest

from tony_tpu.io import (FileSplitReader, array_batches, compute_read_info,
                         full_records_in_split, global_batches,
                         record_size_for, split_length, split_start,
                         to_global_array)
from tony_tpu.io.native.build import load_native


def test_split_tiles_exactly():
    # Property test over many (total, n): splits are contiguous,
    # non-overlapping, and cover [0, total) (reference: TestReader.java:42-60).
    for total in [0, 1, 7, 100, 1023, 65536, 999999]:
        for n in [1, 2, 3, 7, 16]:
            pos = 0
            for idx in range(n):
                assert split_start(total, idx, n) == pos
                pos += split_length(total, idx, n)
            assert pos == total


def test_split_rejects_bad_index():
    with pytest.raises(ValueError):
        split_start(10, 3, 3)
    with pytest.raises(ValueError):
        split_length(10, -1, 3)


def test_compute_read_info_multi_file(tmp_path):
    sizes = [10, 0, 25, 7]
    paths = []
    for i, size in enumerate(sizes):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(bytes(size))
        paths.append(str(p))
    n = 4
    covered = {p: [] for p in paths}
    for idx in range(n):
        for seg in compute_read_info(paths, idx, n):
            covered[seg.path].append((seg.offset, seg.length))
    # Per file: segments tile the file exactly
    for p, size in zip(paths, sizes):
        segs = sorted(covered[p])
        pos = 0
        for off, ln in segs:
            assert off == pos and ln > 0
            pos += ln
        assert pos == size


def _write_fixed(tmp_path, name, rows, record_size):
    data = b"".join(
        bytes([i % 256]) * record_size for i in range(rows))
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


@pytest.mark.parametrize("use_native", [None, False])
def test_fixed_records_read_once_across_tasks(tmp_path, use_native):
    rs = 16
    paths = [_write_fixed(tmp_path, f"f{i}.bin", rows, rs)
             for i, rows in enumerate([13, 0, 29, 5])]
    expect = []
    for p in paths:
        with open(p, "rb") as f:
            data = f.read()
        expect.extend(data[i:i + rs] for i in range(0, len(data), rs))
    n = 3
    got = []
    for idx in range(n):
        with FileSplitReader(paths, idx, n, record_size=rs,
                             use_native=use_native) as r:
            got.extend(r)
    # Every record delivered exactly once, order within task preserved
    assert sorted(got) == sorted(expect)
    assert len(got) == len(expect)


@pytest.mark.parametrize("use_native", [None, False])
def test_newline_records_read_once_across_tasks(tmp_path, use_native):
    lines = [f"record-{i:04d}-{'x' * (i % 37)}".encode() for i in range(211)]
    p1 = tmp_path / "a.jsonl"
    p2 = tmp_path / "b.jsonl"
    p1.write_bytes(b"\n".join(lines[:100]) + b"\n")
    p2.write_bytes(b"\n".join(lines[100:]) + b"\n")
    paths = [str(p1), str(p2)]
    n = 5
    got = []
    for idx in range(n):
        with FileSplitReader(paths, idx, n, use_native=use_native) as r:
            got.extend(r)
    assert sorted(got) == sorted(lines)


def test_shuffle_same_multiset_different_order(tmp_path):
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 500, rs)
    with FileSplitReader([path], record_size=rs, use_native=False) as r:
        plain = list(r)
    with FileSplitReader([path], record_size=rs, shuffle=True, seed=7,
                         capacity=64, use_native=False) as r:
        shuffled = list(r)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_native_lib_builds_and_matches_python(tmp_path):
    lib = load_native()
    if lib is None:
        pytest.skip("no native toolchain")
    rs = 32
    paths = [_write_fixed(tmp_path, f"f{i}.bin", 64, rs) for i in range(3)]
    for idx in range(2):
        with FileSplitReader(paths, idx, 2, record_size=rs,
                             use_native=True) as rn:
            native = list(rn)
            assert rn.is_native
        with FileSplitReader(paths, idx, 2, record_size=rs,
                             use_native=False) as rp:
            python = list(rp)
        assert native == python


def test_native_shuffle_multiset(tmp_path):
    if load_native() is None:
        pytest.skip("no native toolchain")
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 300, rs)
    with FileSplitReader([path], record_size=rs, use_native=True) as r:
        plain = list(r)
    with FileSplitReader([path], record_size=rs, shuffle=True, seed=3,
                         capacity=32, use_native=True) as r:
        shuffled = list(r)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_array_batches_and_global_assembly(tmp_path):
    import jax
    from tony_tpu.parallel import make_mesh

    rows, row_shape, dtype = 64, (4, 2), np.float32
    rs = record_size_for(dtype, row_shape)
    data = np.arange(rows * 8, dtype=dtype).reshape(rows, *row_shape)
    p = tmp_path / "tensors.bin"
    p.write_bytes(data.tobytes())

    mesh = make_mesh({"dp": len(jax.devices())})
    with FileSplitReader([str(p)], record_size=rs) as r:
        batches = list(array_batches(r, 16, dtype, row_shape))
    assert len(batches) == 4
    np.testing.assert_array_equal(np.concatenate(batches), data)

    garr = to_global_array(batches[0], mesh)
    assert garr.shape == (16, 4, 2)
    np.testing.assert_array_equal(np.asarray(garr), batches[0])


def test_short_tail_record_dropped(tmp_path):
    # A file whose size is not a record multiple yields a short tail that
    # must be filtered, not crash the decode.
    dtype, row = np.float32, (4,)
    rs = record_size_for(dtype, row)
    p = tmp_path / "ragged.bin"
    p.write_bytes(np.arange(10 * 4, dtype=dtype).tobytes() + b"\x01\x02\x03")
    with FileSplitReader([str(p)], record_size=rs) as r:
        batches = list(array_batches(r, 4, dtype, row, drop_remainder=False))
    got = np.concatenate(batches)
    assert got.shape == (10, 4)
    np.testing.assert_array_equal(got.ravel(),
                                  np.arange(40, dtype=dtype))


def test_to_global_array_rejects_missing_axis(tmp_path):
    import jax
    from tony_tpu.parallel import make_mesh
    mesh = make_mesh({"fsdp": len(jax.devices())})
    with pytest.raises(ValueError, match="batch_axes"):
        to_global_array(np.zeros((8, 2), np.float32), mesh)
    # Explicit replication is allowed
    garr = to_global_array(np.zeros((8, 2), np.float32), mesh, batch_axes=())
    assert garr.shape == (8, 2)


def test_global_batches_count_agrees_across_processes(tmp_path):
    # Uneven splits: every simulated process must yield the SAME number of
    # batches (min over processes) so multi-host SPMD steps can't deadlock.
    import jax
    from tony_tpu.parallel import make_mesh
    dtype, row = np.float32, (2,)
    rs = record_size_for(dtype, row)
    p = tmp_path / "d.bin"
    np.arange(101 * 2, dtype=dtype).tofile(p)   # 101 records: splits 50/51
    mesh = make_mesh({"dp": len(jax.devices())})
    counts = []
    for pid in range(2):
        n = sum(1 for _ in global_batches([str(p)], 8, dtype, row, mesh,
                                          process_index=pid,
                                          process_count=2))
        counts.append(n)
    assert counts[0] == counts[1] == min(
        full_records_in_split([str(p)], i, 2, rs) // 8 for i in range(2))


def _os_thread_count() -> int:
    # C++ std::thread producers are invisible to threading.active_count();
    # count real kernel tasks so a leaked producer pthread fails the test.
    return len(os.listdir("/proc/self/task"))


def test_native_reader_finalizer_closes(tmp_path):
    if load_native() is None:
        pytest.skip("no native toolchain")
    import gc
    import time
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 5000, rs)
    before = _os_thread_count()
    for _ in range(10):
        r = FileSplitReader([path], record_size=rs, capacity=4)
        next(iter(r))      # abandon mid-iteration, no close()
        del r
    gc.collect()
    deadline = 50
    while _os_thread_count() > before and deadline:
        deadline -= 1
        time.sleep(0.05)
    # Producer threads must not accumulate (they live in C++; each blocked
    # Push would pin a pthread forever without the finalizer).
    assert _os_thread_count() <= before + 1


def test_mid_stream_short_tail_does_not_drop_later_files(tmp_path):
    # Regression: a ragged FIRST file must not end iteration while later
    # files still hold data, and global_batches' deterministic batch count
    # must agree with what the iterator actually yields.
    dtype, row = np.float32, (4,)
    rs = record_size_for(dtype, row)
    p1 = tmp_path / "a.bin"
    p1.write_bytes(np.arange(10 * 4, dtype=dtype).tobytes() + b"\x01\x02\x03")
    p2 = tmp_path / "b.bin"
    np.arange(100, 140, dtype=dtype).tofile(p2)   # 10 more full records
    with FileSplitReader([str(p1), str(p2)], record_size=rs) as r:
        batches = list(array_batches(r, 4, dtype, row))
    assert sum(b.shape[0] for b in batches) == 20  # all 20 full records
    got = np.concatenate(batches)
    np.testing.assert_array_equal(
        got.ravel(), np.concatenate([np.arange(40, dtype=dtype),
                                     np.arange(100, 140, dtype=dtype)]))


def test_reader_next_batch_after_close_returns_empty(tmp_path):
    # Both impls must agree: next_batch on a closed reader is [], not a
    # crash (the native path used to hand C++ a NULL handle).
    rs = 8
    path = _write_fixed(tmp_path, "c.bin", 64, rs)
    for use_native in ([False, True] if load_native() else [False]):
        r = FileSplitReader([path], record_size=rs, use_native=use_native)
        assert r.next_batch(2)
        r.close()
        assert r.next_batch(2) == []
