"""Data-feed IO tests, mirroring the reference's TestReader.java: split
offsets tile the byte range exactly (:42-60), multi-file read correctness
(:66+), and shuffle mode — plus native-vs-python parity and the jax feed."""

import json
import os

import numpy as np
import pytest

from tony_tpu.io import (FileSplitReader, array_batches, compute_read_info,
                         full_records_in_split, global_batches,
                         record_size_for, split_length, split_start,
                         to_global_array)
from tony_tpu.io.native.build import load_native


def test_split_tiles_exactly():
    # Property test over many (total, n): splits are contiguous,
    # non-overlapping, and cover [0, total) (reference: TestReader.java:42-60).
    for total in [0, 1, 7, 100, 1023, 65536, 999999]:
        for n in [1, 2, 3, 7, 16]:
            pos = 0
            for idx in range(n):
                assert split_start(total, idx, n) == pos
                pos += split_length(total, idx, n)
            assert pos == total


def test_split_rejects_bad_index():
    with pytest.raises(ValueError):
        split_start(10, 3, 3)
    with pytest.raises(ValueError):
        split_length(10, -1, 3)


def test_compute_read_info_multi_file(tmp_path):
    sizes = [10, 0, 25, 7]
    paths = []
    for i, size in enumerate(sizes):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(bytes(size))
        paths.append(str(p))
    n = 4
    covered = {p: [] for p in paths}
    for idx in range(n):
        for seg in compute_read_info(paths, idx, n):
            covered[seg.path].append((seg.offset, seg.length))
    # Per file: segments tile the file exactly
    for p, size in zip(paths, sizes):
        segs = sorted(covered[p])
        pos = 0
        for off, ln in segs:
            assert off == pos and ln > 0
            pos += ln
        assert pos == size


def _write_fixed(tmp_path, name, rows, record_size):
    data = b"".join(
        bytes([i % 256]) * record_size for i in range(rows))
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


@pytest.mark.parametrize("use_native", [None, False])
def test_fixed_records_read_once_across_tasks(tmp_path, use_native):
    rs = 16
    paths = [_write_fixed(tmp_path, f"f{i}.bin", rows, rs)
             for i, rows in enumerate([13, 0, 29, 5])]
    expect = []
    for p in paths:
        with open(p, "rb") as f:
            data = f.read()
        expect.extend(data[i:i + rs] for i in range(0, len(data), rs))
    n = 3
    got = []
    for idx in range(n):
        with FileSplitReader(paths, idx, n, record_size=rs,
                             use_native=use_native) as r:
            got.extend(r)
    # Every record delivered exactly once, order within task preserved
    assert sorted(got) == sorted(expect)
    assert len(got) == len(expect)


@pytest.mark.parametrize("use_native", [None, False])
def test_newline_records_read_once_across_tasks(tmp_path, use_native):
    lines = [f"record-{i:04d}-{'x' * (i % 37)}".encode() for i in range(211)]
    p1 = tmp_path / "a.jsonl"
    p2 = tmp_path / "b.jsonl"
    p1.write_bytes(b"\n".join(lines[:100]) + b"\n")
    p2.write_bytes(b"\n".join(lines[100:]) + b"\n")
    paths = [str(p1), str(p2)]
    n = 5
    got = []
    for idx in range(n):
        with FileSplitReader(paths, idx, n, use_native=use_native) as r:
            got.extend(r)
    assert sorted(got) == sorted(lines)


def test_shuffle_same_multiset_different_order(tmp_path):
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 500, rs)
    with FileSplitReader([path], record_size=rs, use_native=False) as r:
        plain = list(r)
    with FileSplitReader([path], record_size=rs, shuffle=True, seed=7,
                         capacity=64, use_native=False) as r:
        shuffled = list(r)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_native_lib_builds_and_matches_python(tmp_path):
    lib = load_native()
    if lib is None:
        pytest.skip("no native toolchain")
    rs = 32
    paths = [_write_fixed(tmp_path, f"f{i}.bin", 64, rs) for i in range(3)]
    for idx in range(2):
        with FileSplitReader(paths, idx, 2, record_size=rs,
                             use_native=True) as rn:
            native = list(rn)
            assert rn.is_native
        with FileSplitReader(paths, idx, 2, record_size=rs,
                             use_native=False) as rp:
            python = list(rp)
        assert native == python


def test_native_shuffle_multiset(tmp_path):
    if load_native() is None:
        pytest.skip("no native toolchain")
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 300, rs)
    with FileSplitReader([path], record_size=rs, use_native=True) as r:
        plain = list(r)
    with FileSplitReader([path], record_size=rs, shuffle=True, seed=3,
                         capacity=32, use_native=True) as r:
        shuffled = list(r)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_array_batches_and_global_assembly(tmp_path):
    import jax
    from tony_tpu.parallel import make_mesh

    rows, row_shape, dtype = 64, (4, 2), np.float32
    rs = record_size_for(dtype, row_shape)
    data = np.arange(rows * 8, dtype=dtype).reshape(rows, *row_shape)
    p = tmp_path / "tensors.bin"
    p.write_bytes(data.tobytes())

    mesh = make_mesh({"dp": len(jax.devices())})
    with FileSplitReader([str(p)], record_size=rs) as r:
        batches = list(array_batches(r, 16, dtype, row_shape))
    assert len(batches) == 4
    np.testing.assert_array_equal(np.concatenate(batches), data)

    garr = to_global_array(batches[0], mesh)
    assert garr.shape == (16, 4, 2)
    np.testing.assert_array_equal(np.asarray(garr), batches[0])


def test_short_tail_record_dropped(tmp_path):
    # A file whose size is not a record multiple yields a short tail that
    # must be filtered, not crash the decode.
    dtype, row = np.float32, (4,)
    rs = record_size_for(dtype, row)
    p = tmp_path / "ragged.bin"
    p.write_bytes(np.arange(10 * 4, dtype=dtype).tobytes() + b"\x01\x02\x03")
    with FileSplitReader([str(p)], record_size=rs) as r:
        batches = list(array_batches(r, 4, dtype, row, drop_remainder=False))
    got = np.concatenate(batches)
    assert got.shape == (10, 4)
    np.testing.assert_array_equal(got.ravel(),
                                  np.arange(40, dtype=dtype))


def test_to_global_array_rejects_missing_axis(tmp_path):
    import jax
    from tony_tpu.parallel import make_mesh
    mesh = make_mesh({"fsdp": len(jax.devices())})
    with pytest.raises(ValueError, match="batch_axes"):
        to_global_array(np.zeros((8, 2), np.float32), mesh)
    # Explicit replication is allowed
    garr = to_global_array(np.zeros((8, 2), np.float32), mesh, batch_axes=())
    assert garr.shape == (8, 2)


def test_global_batches_count_agrees_across_processes(tmp_path):
    # Uneven splits: every simulated process must yield the SAME number of
    # batches (min over processes) so multi-host SPMD steps can't deadlock.
    import jax
    from tony_tpu.parallel import make_mesh
    dtype, row = np.float32, (2,)
    rs = record_size_for(dtype, row)
    p = tmp_path / "d.bin"
    np.arange(101 * 2, dtype=dtype).tofile(p)   # 101 records: splits 50/51
    mesh = make_mesh({"dp": len(jax.devices())})
    counts = []
    for pid in range(2):
        n = sum(1 for _ in global_batches([str(p)], 8, dtype, row, mesh,
                                          process_index=pid,
                                          process_count=2))
        counts.append(n)
    assert counts[0] == counts[1] == min(
        full_records_in_split([str(p)], i, 2, rs) // 8 for i in range(2))


def _os_thread_count() -> int:
    # C++ std::thread producers are invisible to threading.active_count();
    # count real kernel tasks so a leaked producer pthread fails the test.
    return len(os.listdir("/proc/self/task"))


def test_native_reader_finalizer_closes(tmp_path):
    if load_native() is None:
        pytest.skip("no native toolchain")
    import gc
    import time
    rs = 8
    path = _write_fixed(tmp_path, "f.bin", 5000, rs)
    before = _os_thread_count()
    for _ in range(10):
        r = FileSplitReader([path], record_size=rs, capacity=4)
        next(iter(r))      # abandon mid-iteration, no close()
        del r
    gc.collect()
    deadline = 50
    while _os_thread_count() > before and deadline:
        deadline -= 1
        time.sleep(0.05)
    # Producer threads must not accumulate (they live in C++; each blocked
    # Push would pin a pthread forever without the finalizer).
    assert _os_thread_count() <= before + 1


def test_mid_stream_short_tail_does_not_drop_later_files(tmp_path):
    # Regression: a ragged FIRST file must not end iteration while later
    # files still hold data, and global_batches' deterministic batch count
    # must agree with what the iterator actually yields.
    dtype, row = np.float32, (4,)
    rs = record_size_for(dtype, row)
    p1 = tmp_path / "a.bin"
    p1.write_bytes(np.arange(10 * 4, dtype=dtype).tobytes() + b"\x01\x02\x03")
    p2 = tmp_path / "b.bin"
    np.arange(100, 140, dtype=dtype).tofile(p2)   # 10 more full records
    with FileSplitReader([str(p1), str(p2)], record_size=rs) as r:
        batches = list(array_batches(r, 4, dtype, row))
    assert sum(b.shape[0] for b in batches) == 20  # all 20 full records
    got = np.concatenate(batches)
    np.testing.assert_array_equal(
        got.ravel(), np.concatenate([np.arange(40, dtype=dtype),
                                     np.arange(100, 140, dtype=dtype)]))


def test_short_tail_warns_once_per_reader_not_per_call_site(tmp_path,
                                                            caplog):
    # Two short-tailed files consumed through TWO array_batches call sites
    # over the SAME reader (the spill / mixed-delivery pattern): the drop
    # warning fires once per reader, while every full record still arrives.
    import logging

    dtype, row = np.float32, (2,)
    rs = record_size_for(dtype, row)
    paths = []
    # short tails in files 0 and 2, placed so call site 1 consumes the
    # first tail and call site 2 the second
    for i, (n, tail) in enumerate([(2, b"xy"), (3, b""), (2, b"zzz"),
                                   (3, b"")]):
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(np.arange(i * 100, i * 100 + n * 2,
                                dtype=dtype).tobytes() + tail)
        paths.append(str(p))
    with caplog.at_level(logging.WARNING, logger="tony_tpu.io.jax_feed"):
        with FileSplitReader(paths, record_size=rs, use_native=False) as r:
            first = [next(array_batches(r, 4, dtype, row,
                                        drop_remainder=False))]
            rest = list(array_batches(r, 4, dtype, row,
                                      drop_remainder=False))
    assert sum(b.shape[0] for b in first + rest) == 10   # all full records
    tails = [rec for rec in caplog.records if "short tail" in rec.message]
    assert len(tails) == 1


def test_reader_close_timeout_drops_queue_reference(monkeypatch, caplog):
    """A prefetch thread wedged in hung IO must not pin decoded records:
    the close-timeout path warns, drains the queue, and drops the reader's
    (and finalizer's) reference so records are GC-able."""
    import logging
    import threading
    import time as _time

    from tony_tpu.io import reader as reader_mod

    release = threading.Event()

    def hung_generate(segments, record_size):
        yield b"x" * 8
        release.wait()          # hung IO: stop cannot interrupt this
        yield b"y" * 8

    monkeypatch.setattr(reader_mod._PythonImpl, "_generate",
                        staticmethod(hung_generate))
    impl = reader_mod._PythonImpl([], 8, capacity=4, shuffle=False, seed=0,
                                  prefetch=True)
    try:
        deadline = _time.monotonic() + 5
        while impl._queue.qsize() < 1 and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert impl._queue.qsize() >= 1     # first record decoded + parked
        with caplog.at_level(logging.WARNING, logger="tony_tpu.io.reader"):
            t0 = _time.monotonic()
            impl.close()                    # join times out (thread wedged)
        assert _time.monotonic() - t0 < 10
        assert impl._queue is None          # records released, not pinned
        assert any("did not exit" in r.message for r in caplog.records)
    finally:
        release.set()                       # let the daemon thread finish
        impl._producer.join(timeout=5)
    assert not impl._producer.is_alive()


def test_reader_next_batch_after_close_returns_empty(tmp_path):
    # Both impls must agree: next_batch on a closed reader is [], not a
    # crash (the native path used to hand C++ a NULL handle).
    rs = 8
    path = _write_fixed(tmp_path, "c.bin", 64, rs)
    for use_native in ([False, True] if load_native() else [False]):
        r = FileSplitReader([path], record_size=rs, use_native=use_native)
        assert r.next_batch(2)
        r.close()
        assert r.next_batch(2) == []


# ---------------------------------------------------------------------------
# TONY1 framed format: schema channel, boundary sync, spill delivery
# (reference: HdfsAvroFileSplitReader.java:103-133 delivery modes, :242
# block sync, :446 getSchemaJson)
# ---------------------------------------------------------------------------
def _write_framed(tmp_path, name, records, schema=None, block_bytes=200):
    from tony_tpu.io.framed import FramedWriter
    p = tmp_path / name
    with FramedWriter(str(p), schema=schema or {}, block_bytes=block_bytes) as w:
        for r in records:
            w.append(r)
    return str(p)


def _varlen_records(n, tag=b"r"):
    # lengths vary 0..400 bytes; payloads include sync-like noise
    import random
    rng = random.Random(7)
    return [tag + b"-%04d-" % i + bytes(rng.randrange(256)
            for _ in range(rng.randrange(0, 400))) for i in range(n)]


@pytest.mark.parametrize("use_native", [None, False])
def test_framed_varlen_records_read_once_across_tasks(tmp_path, use_native):
    """Variable-length records round-trip across byte-range splits: every
    record delivered exactly once, however the split boundaries land."""
    recs = _varlen_records(307)
    paths = [_write_framed(tmp_path, "a.tony1", recs[:140]),
             _write_framed(tmp_path, "b.tony1", recs[140:])]
    for n in (1, 3, 7):
        got = []
        for idx in range(n):
            with FileSplitReader(paths, idx, n,
                                 use_native=use_native) as r:
                got.extend(r)
        assert len(got) == len(recs), f"n={n}"
        assert sorted(got) == sorted(recs), f"n={n}"


def test_framed_native_matches_python(tmp_path):
    from tony_tpu.io.native.build import load_native
    if load_native() is None:
        pytest.skip("no native toolchain")
    recs = _varlen_records(97)
    path = _write_framed(tmp_path, "p.tony1", recs, block_bytes=64)
    for idx in range(3):
        with FileSplitReader([path], idx, 3, use_native=True) as rn, \
                FileSplitReader([path], idx, 3, use_native=False) as rp:
            assert list(rn) == list(rp)


def test_framed_schema_channel(tmp_path):
    """The schema JSON written into the file header reaches the reader —
    the getSchemaJson:446 analog."""
    schema = {"fields": [{"name": "x", "type": "float32", "shape": [4]}],
              "version": 2}
    path = _write_framed(tmp_path, "s.tony1", [b"abc"], schema=schema)
    with FileSplitReader([path]) as r:
        assert r.record_size == -1          # auto-detected framed
        assert r.schema() == schema
    # unframed data has an empty schema channel
    p2 = tmp_path / "plain.jsonl"
    p2.write_bytes(b"x\ny\n")
    with FileSplitReader([str(p2)]) as r2:
        assert r2.record_size == 0
        assert r2.schema() == {}


def test_framed_empty_and_tiny_splits(tmp_path):
    """More tasks than blocks: surplus splits deliver nothing, nothing is
    lost or duplicated."""
    recs = [b"one", b"two", b"three"]
    path = _write_framed(tmp_path, "t.tony1", recs, block_bytes=1)  # 1/block
    got = []
    for idx in range(16):
        with FileSplitReader([path], idx, 16, use_native=False) as r:
            got.extend(r)
    assert sorted(got) == sorted(recs)


@pytest.mark.parametrize("use_native", [None, False])
def test_spill_mode_feeds_batch_bigger_than_buffer(tmp_path, use_native):
    """Local-spill delivery: a batch far larger than the 4MiB pull buffer
    and the prefetch pool lands on disk intact (nextBatchFileLocalSpill
    analog)."""
    from tony_tpu.io.framed import iter_file_records
    # ~12 MiB of records vs the 4 MiB pull buffer and capacity=8 pool
    recs = [bytes([i % 251]) * 65536 for i in range(190)]
    path = _write_framed(tmp_path, "big.tony1", recs,
                         block_bytes=1 << 20)
    with FileSplitReader([path], 0, 1, capacity=8,
                         use_native=use_native) as r:
        spill = r.next_batch_spill(str(tmp_path / "spill"))
        assert spill is not None
        got = list(iter_file_records(spill))
        assert r.next_batch_spill(str(tmp_path / "spill")) is None  # EOF
    assert got == recs
    import os
    assert os.path.getsize(spill) > 4 * (1 << 20)


def test_spill_mode_respects_max_bytes(tmp_path):
    """max_bytes chunks the split into several spill files."""
    from tony_tpu.io.framed import iter_file_records
    recs = [b"%05d" % i + b"x" * 100 for i in range(500)]
    path = _write_framed(tmp_path, "c.tony1", recs)
    got, files = [], 0
    with FileSplitReader([path], use_native=False) as r:
        while True:
            spill = r.next_batch_spill(str(tmp_path / "sp"),
                                       max_bytes=8192)
            if spill is None:
                break
            files += 1
            got.extend(iter_file_records(spill))
    assert files > 3
    assert got == recs


def test_framed_corruption_detected(tmp_path):
    from tony_tpu.io.framed import FramedFormatError, iter_file_records
    path = _write_framed(tmp_path, "x.tony1", [b"hello", b"world"])
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0xFF                       # flip a payload byte: still reads
    open(path, "wb").write(bytes(data))
    assert len(list(iter_file_records(path))) == 2
    # corrupt the first block's record COUNT (header is 26B fixed + 2B
    # "{}" schema = data at 28; count at 28+16..+20): implausible count
    # must raise, not wander off into garbage
    data[47] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(FramedFormatError):
        list(iter_file_records(path))
    # a damaged SYNC MARKER makes the block unreachable by design (scan
    # semantics) — data loss is silent, like a torn Avro block
    data[47] ^= 0xFF                       # restore count
    data[30] ^= 0xFF                       # corrupt first block sync
    open(path, "wb").write(bytes(data))
    assert list(iter_file_records(path)) == []


def test_framed_corrupt_record_length_raises_both_engines(tmp_path):
    """Engine parity: a corrupt record-length field raises in BOTH the
    Python and C++ paths — never silent truncation."""
    from tony_tpu.io.framed import FramedFormatError
    from tony_tpu.io.native.build import load_native
    path = _write_framed(tmp_path, "cl.tony1", [b"A" * 10, b"B" * 10],
                         block_bytes=1 << 20)
    data = bytearray(open(path, "rb").read())
    # layout: 26B header + 2B "{}" + sync(16) + count(4) + size(4) + payload;
    # first record length field sits at 28+24
    data[28 + 24] = 200
    open(path, "wb").write(bytes(data))
    with pytest.raises(FramedFormatError):
        list(FileSplitReader([path], use_native=False))
    if load_native() is not None:
        with pytest.raises(Exception):
            list(FileSplitReader([path], use_native=True))


def test_framed_mixed_inputs_rejected(tmp_path):
    path = _write_framed(tmp_path, "m.tony1", [b"x"])
    plain = tmp_path / "m.jsonl"
    plain.write_bytes(b"line\n")
    with pytest.raises(ValueError, match="mixed framings"):
        FileSplitReader([str(plain), path])
    with pytest.raises(ValueError, match="mixed framings"):
        FileSplitReader([path, str(plain)])


def test_framed_missing_path_raises_file_not_found(tmp_path):
    """A typo'd path must surface as the OS error, not be misdiagnosed as a
    framing mismatch by auto-detection."""
    path = _write_framed(tmp_path, "ok.tony1", [b"x"])
    with pytest.raises(FileNotFoundError):
        FileSplitReader([path, str(tmp_path / "nope.tony1")])


def test_framed_truncated_trailing_sync_raises_both_engines(tmp_path):
    """Engine parity: a writer that died mid-sync-marker (1..15 trailing
    bytes) raises in BOTH engines instead of silently ending the split."""
    from tony_tpu.io.framed import FramedFormatError
    from tony_tpu.io.native.build import load_native
    path = _write_framed(tmp_path, "t.tony1", [b"A" * 10, b"B" * 10],
                         block_bytes=1 << 20)
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03\x04\x05")     # 5-byte torn marker
    with pytest.raises(FramedFormatError):
        list(FileSplitReader([path], use_native=False))
    if load_native() is not None:
        with pytest.raises(Exception):
            list(FileSplitReader([path], use_native=True))


def test_spill_header_larger_than_budget_still_progresses(tmp_path):
    """A schema header bigger than max_bytes must not fake end-of-split:
    every call delivers at least one record until truly drained."""
    from tony_tpu.io.framed import iter_file_records
    schema = {"pad": "x" * 20000}           # ~20KB header
    recs = [b"%03d" % i for i in range(10)]
    path = _write_framed(tmp_path, "h.tony1", recs, schema=schema)
    got = []
    with FileSplitReader([path], use_native=False) as r:
        while True:
            spill = r.next_batch_spill(str(tmp_path / "sp"), max_bytes=1024)
            if spill is None:
                break
            got.extend(iter_file_records(spill))
    assert got == recs


def test_convert_jsonl_roundtrip(tmp_path):
    """tony convert: jsonl → TONY1; records and schema survive the round
    trip through the real reader."""
    from tony_tpu.client import cli
    src = tmp_path / "corpus.jsonl"
    recs = [{"text": f"doc {i}", "id": i} for i in range(100)]
    src.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    rc = cli.main(["convert", str(src), "--out-dir", str(tmp_path / "f")])
    assert rc == 0
    out = tmp_path / "f" / "corpus.tony1"
    with FileSplitReader([str(out)], use_native=False) as r:
        got = [json.loads(b) for b in r]
    assert got == recs
    with FileSplitReader([str(out)], use_native=False) as r:
        assert json.loads(r.schema_json) == {"format": "jsonl"}


def test_convert_fixed_records_and_short_tail(tmp_path):
    from tony_tpu.io.convert import convert_file
    src = tmp_path / "d.bin"
    src.write_bytes(bytes(range(40)))
    dest = str(tmp_path / "d.tony1")
    n = convert_file(str(src), dest, "fixed", {"rs": 8}, record_size=8)
    assert n == 5
    with FileSplitReader([dest], use_native=False) as r:
        assert list(r)[0] == bytes(range(8))
    bad = tmp_path / "bad.bin"
    bad.write_bytes(bytes(42))
    with pytest.raises(ValueError, match="trailing"):
        convert_file(str(bad), str(tmp_path / "x"), "fixed", {},
                     record_size=8)
    assert not os.path.exists(tmp_path / "x")   # no half-framed leftovers


def test_convert_rejects_bad_jsonl(tmp_path):
    from tony_tpu.io.convert import convert_file
    src = tmp_path / "bad.jsonl"
    src.write_text('{"ok": 1}\nnot-json\n')
    with pytest.raises(json.JSONDecodeError):
        convert_file(str(src), str(tmp_path / "o"), "jsonl", {})


def test_convert_stem_collision_rejected(tmp_path):
    from tony_tpu.client import cli
    (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
    (tmp_path / "a" / "c.jsonl").write_text('{"x":1}\n')
    (tmp_path / "b" / "c.jsonl").write_text('{"x":2}\n')
    with pytest.raises(SystemExit):
        cli.main(["convert", str(tmp_path / "a" / "c.jsonl"),
                  str(tmp_path / "b" / "c.jsonl"),
                  "--out-dir", str(tmp_path / "o")])


def test_convert_option_first_and_tmp_cleanup(tmp_path):
    from tony_tpu.client import cli
    src = tmp_path / "x.txt"
    src.write_text("one\ntwo\n")
    # leading option must reach the converter's parser
    rc = cli.main(["convert", "--format", "lines", str(src),
                   "--out-dir", str(tmp_path / "o")])
    assert rc == 0
    with FileSplitReader([str(tmp_path / "o" / "x.tony1")],
                         use_native=False) as r:
        assert list(r) == [b"one", b"two"]
    # a failing conversion leaves neither dest nor dest.tmp behind
    from tony_tpu.io.convert import convert_file
    bad = tmp_path / "bad.bin"
    bad.write_bytes(bytes(42))
    with pytest.raises(ValueError):
        convert_file(str(bad), str(tmp_path / "y"), "fixed", {},
                     record_size=8)
    assert not os.path.exists(tmp_path / "y")
    assert not os.path.exists(tmp_path / "y.tmp")


# ---------------------------------------------------------------------------
# Avro object-container ingestion (tony_tpu.io.avro): existing Avro data
# read in place — the reference's native format (HdfsAvroFileSplitReader)
# ---------------------------------------------------------------------------

_AVRO_SCHEMA = {
    "type": "record", "name": "Row", "namespace": "tony.test",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "payload", "type": "bytes"},
        {"name": "tag", "type": ["null", "string"]},
    ],
}


def _avro_rows(n, seed=7):
    import random
    rng = random.Random(seed)
    return [{"id": i,
             "payload": bytes(rng.randrange(256)
                              for _ in range(rng.randrange(0, 300))),
             "tag": None if i % 3 == 0 else f"t{i}"}
            for i in range(n)]


def _write_avro(tmp_path, name, rows, codec="null", block_records=16):
    from tony_tpu.io.avro import AvroWriter
    path = str(tmp_path / name)
    with AvroWriter(path, _AVRO_SCHEMA, codec=codec,
                    block_records=block_records) as w:
        for row in rows:
            w.append(row)
    return path


def test_avro_datum_codec_roundtrip():
    """Every Avro type through write_datum → read_datum → identity, and
    skip_datum lands exactly on the boundary."""
    from tony_tpu.io.avro import (parse_schema, read_datum, skip_datum,
                                  write_datum)
    schema = parse_schema(json.dumps({
        "type": "record", "name": "All",
        "fields": [
            {"name": "n", "type": "null"},
            {"name": "b", "type": "boolean"},
            {"name": "i", "type": "int"},
            {"name": "l", "type": "long"},
            {"name": "f", "type": "float"},
            {"name": "d", "type": "double"},
            {"name": "s", "type": "string"},
            {"name": "by", "type": "bytes"},
            {"name": "fx", "type": {"type": "fixed", "name": "F16",
                                    "size": 4}},
            {"name": "e", "type": {"type": "enum", "name": "E",
                                   "symbols": ["A", "B", "C"]}},
            {"name": "u", "type": ["null", "long", "string"]},
            {"name": "arr", "type": {"type": "array", "items": "long"}},
            {"name": "m", "type": {"type": "map", "values": "double"}},
            {"name": "nested", "type": {
                "type": "record", "name": "Inner",
                "fields": [{"name": "x", "type": "long"},
                           {"name": "again", "type": ["null", "Inner"]}]}},
        ]}))
    value = {"n": None, "b": True, "i": -123, "l": 1 << 40, "f": 0.5,
             "d": -2.25, "s": "héllo", "by": b"\x00\xff", "fx": b"abcd",
             "e": "B", "u": "pick-me",
             "arr": [1, -2, 3_000_000_000], "m": {"k1": 1.5, "k2": -0.5},
             "nested": {"x": 7, "again": {"x": 8, "again": None}}}
    out = bytearray()
    write_datum(schema, value, out)
    got, end = read_datum(schema, memoryview(bytes(out)), 0)
    assert end == len(out)
    assert got == value
    assert skip_datum(schema, memoryview(bytes(out)), 0) == len(out)


@pytest.mark.parametrize("codec", ["null", "deflate", "snappy"])
def test_avro_records_read_once_across_tasks(tmp_path, codec):
    """The reference's split-tiling property (TestReader.java:42-60) on raw
    Avro containers: every record delivered exactly once for any task
    count, including blocks straddling split boundaries."""
    from tony_tpu.io.avro import read_datum, read_path_header
    rows = _avro_rows(211)
    paths = [_write_avro(tmp_path, "a.avro", rows[:100], codec=codec,
                         block_records=7),
             _write_avro(tmp_path, "b.avro", rows[100:], codec=codec,
                         block_records=13)]
    header = read_path_header(paths[0])
    for n in (1, 3, 7):
        got = []
        for idx in range(n):
            with FileSplitReader(paths, idx, n) as r:
                assert r.record_size == -2      # auto-detected Avro
                for raw in r:
                    v, _ = read_datum(header.schema, memoryview(raw), 0)
                    got.append(v)
        assert sorted(got, key=lambda v: v["id"]) == rows, f"n={n}"


def test_avro_schema_channel(tmp_path):
    path = _write_avro(tmp_path, "s.avro", _avro_rows(5))
    with FileSplitReader([path]) as r:
        assert r.schema()["name"] == "Row"
        assert r.schema()["fields"][0]["name"] == "id"


def test_avro_shuffle_same_multiset(tmp_path):
    path = _write_avro(tmp_path, "sh.avro", _avro_rows(64), block_records=4)
    with FileSplitReader([path]) as plain:
        ordered = list(plain)
    with FileSplitReader([path], shuffle=True, seed=3,
                         capacity=8) as shuf:
        shuffled = list(shuf)
    assert sorted(shuffled) == sorted(ordered)
    assert shuffled != ordered


def test_avro_spill_mode(tmp_path):
    """Avro source → local spill (TONY1 framed) → records round-trip with
    the Avro schema riding the spill file's schema channel."""
    from tony_tpu.io.framed import iter_file_records, read_path_header
    path = _write_avro(tmp_path, "sp.avro", _avro_rows(50), block_records=9)
    with FileSplitReader([path]) as direct:
        want = list(direct)
    got = []
    with FileSplitReader([path]) as r:
        while True:
            spill = r.next_batch_spill(str(tmp_path / "spill"),
                                       max_records=17)
            if spill is None:
                break
            assert read_path_header(spill).schema["name"] == "Row"
            got.extend(iter_file_records(spill))
    assert got == want


def test_avro_use_native_requested_raises(tmp_path):
    from tony_tpu.io import DataFeedError
    path = _write_avro(tmp_path, "n.avro", _avro_rows(3))
    with pytest.raises(DataFeedError, match="native"):
        FileSplitReader([path], use_native=True)


def test_avro_mixed_inputs_rejected(tmp_path):
    path = _write_avro(tmp_path, "m.avro", _avro_rows(3))
    plain = tmp_path / "plain.jsonl"
    plain.write_text("x\n")
    with pytest.raises(ValueError, match="mixed framings"):
        FileSplitReader([path, str(plain)])


def test_avro_corruption_detected(tmp_path):
    from tony_tpu.io.avro import AvroFormatError
    path = _write_avro(tmp_path, "c.avro", _avro_rows(40), block_records=5)
    data = bytearray(open(path, "rb").read())
    # clobber the sync marker after the first block: readers must not
    # silently resynchronize onto garbage
    from tony_tpu.io.avro import read_path_header
    hdr = read_path_header(path)
    first_sync_after = bytes(data).find(hdr.sync, hdr.data_start)
    assert first_sync_after != -1
    data[first_sync_after:first_sync_after + 4] = b"XXXX"
    bad = tmp_path / "bad.avro"
    bad.write_bytes(bytes(data))
    with pytest.raises(AvroFormatError):
        with FileSplitReader([str(bad)]) as r:
            list(r)


def test_snappy_decoder_against_handcrafted_vectors():
    """Decoder checked against streams written by hand from the format
    spec — independent of this repo's encoder: literals (short + extended
    length), copy-1 with an OVERLAPPING run (offset < length, the
    RLE-style case), copy-2."""
    from tony_tpu.io import snappy

    # "ab" literal then copy-1 len=10 off=2 → "ab" * 6
    raw = bytes([12, (2 - 1) << 2]) + b"ab" \
        + bytes([1 | ((10 - 4) << 2) | ((2 >> 8) << 5), 2])
    assert snappy.decompress(raw) == b"ab" * 6

    # extended literal length: tag 60<<2, one length byte (100-1)
    payload = bytes(range(100))
    raw = snappy._write_varint(100) + bytes([60 << 2, 99]) + payload
    assert snappy.decompress(raw) == payload

    # copy-2: literal "abcd", copy len=4 off=4 via 2-byte offset
    raw = snappy._write_varint(8) + bytes([(4 - 1) << 2]) + b"abcd" \
        + bytes([2 | ((4 - 1) << 2)]) + (4).to_bytes(2, "little")
    assert snappy.decompress(raw) == b"abcdabcd"

    # malformed: offset outside the written window
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(snappy._write_varint(8) + bytes([1 | 0, 5]))
    # malformed: preamble promises more than the stream yields
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(snappy._write_varint(50) + bytes([(2 - 1) << 2])
                          + b"ab")


def test_snappy_compressor_roundtrip():
    from tony_tpu.io import snappy

    cases = [b"", b"a", b"ab" * 500, bytes(range(256)) * 7,
             b"the quick brown fox " * 64, os.urandom(4096),
             b"\x00" * 10000]
    for data in cases:
        comp = snappy.compress(data)
        assert snappy.decompress(comp) == data
    # repetitive data must actually shrink (copies are being emitted)
    assert len(snappy.compress(b"ab" * 500)) < 100


def test_avro_snappy_crc_detects_corruption(tmp_path):
    """Avro snappy framing carries a CRC32 of the uncompressed block; a
    bit-flip inside the compressed payload must fail loudly even when
    the stream still decompresses."""
    import io as _io

    from tony_tpu.io.avro import (AvroFormatError, _read_long_io,
                                  read_path_header)
    path = _write_avro(tmp_path, "s.avro", _avro_rows(30), codec="snappy",
                       block_records=30)
    data = bytearray(open(path, "rb").read())
    hdr = read_path_header(path)
    f = _io.BytesIO(bytes(data))
    f.seek(hdr.data_start)
    _read_long_io(f)                      # record count
    size = _read_long_io(f)               # block byte size (incl. CRC)
    block_start = f.tell()

    # 1) flip a stored-CRC byte: payload decompresses fine, CRC must trip
    bad = bytearray(data)
    bad[block_start + size - 1] ^= 0xFF
    p1 = tmp_path / "badcrc.avro"
    p1.write_bytes(bytes(bad))
    with pytest.raises(AvroFormatError, match="CRC mismatch"):
        with FileSplitReader([str(p1)]) as r:
            list(r)

    # 2) flip a payload byte: snappy structure breaks, wrapped loudly
    bad = bytearray(data)
    bad[block_start + 4] ^= 0xFF
    p2 = tmp_path / "badpayload.avro"
    p2.write_bytes(bytes(bad))
    with pytest.raises(AvroFormatError, match="CRC mismatch|corrupt snappy"):
        with FileSplitReader([str(p2)]) as r:
            list(r)


def test_avro_empty_and_tiny_splits(tmp_path):
    """More tasks than blocks: surplus splits deliver nothing and nothing
    is lost (single-record blocks maximize boundary cases)."""
    rows = _avro_rows(9)
    path = _write_avro(tmp_path, "t.avro", rows, block_records=1)
    got = []
    for idx in range(16):
        with FileSplitReader([path], idx, 16) as r:
            got.extend(r)
    from tony_tpu.io.avro import read_datum, read_path_header
    hdr = read_path_header(path)
    ids = sorted(read_datum(hdr.schema, memoryview(g), 0)[0]["id"]
                 for g in got)
    assert ids == [row["id"] for row in rows]


def test_avro_prefetch_thread_and_error_propagation(tmp_path):
    """The Avro arm runs on the Python engine's PREFETCH thread: records
    arrive identically to the synchronous path (same FIFO window, same
    shuffle determinism), close() reaps the thread, and a decode error in
    the producer surfaces on the consumer, not in a dead daemon."""
    import threading
    path = _write_avro(tmp_path, "p.avro", _avro_rows(120), block_records=8)
    with FileSplitReader([path]) as r:
        assert not r.is_native and r._impl._producer is not None
        want_thread = r._impl._producer
        plain = list(r)
    assert not want_thread.is_alive()          # close() joined it
    # sync-path oracle: force prefetch off via the class directly
    from tony_tpu.io.reader import _PythonImpl
    from tony_tpu.io.split import compute_read_info
    sync = _PythonImpl(compute_read_info([path], 0, 1), -2, 1024,
                       False, 0, prefetch=False)
    assert plain == sync.next_batch(10_000)
    # deterministic shuffle across the thread boundary
    with FileSplitReader([path], shuffle=True, seed=5) as a, \
            FileSplitReader([path], shuffle=True, seed=5) as b:
        assert list(a) == list(b)
    # corruption mid-file: the producer's error reaches next_batch
    data = bytearray(open(path, "rb").read())
    from tony_tpu.io.avro import AvroFormatError, read_path_header
    hdr = read_path_header(path)
    at = bytes(data).find(hdr.sync, hdr.data_start)
    data[at:at + 4] = b"XXXX"
    bad = tmp_path / "bad.avro"
    bad.write_bytes(bytes(data))
    with pytest.raises(AvroFormatError):
        with FileSplitReader([str(bad)]) as rb:
            list(rb)


def _fake_gcs(tmp_path, monkeypatch):
    """Route gs:// through tests/fake_gsutil.py on a tmpdir (the MiniDFS
    trick); returns the local root backing gs://bucket/..."""
    import sys as _sys

    from tony_tpu.storage import GcsStorage, register_storage

    root = tmp_path / "gcs"
    root.mkdir(exist_ok=True)
    monkeypatch.setenv("FAKE_GCS_ROOT", str(root))
    shim = tmp_path / "gsutil"
    fake = os.path.join(os.path.dirname(__file__), "fake_gsutil.py")
    shim.write_text(f"#!/bin/bash\nexec {_sys.executable} {fake} \"$@\"\n")
    shim.chmod(0o755)
    register_storage("gs", GcsStorage(gsutil=str(shim)))
    return root


@pytest.mark.parametrize("kind", ["avro", "framed", "lines", "fixed"])
def test_gs_paths_split_identically_to_local(tmp_path, monkeypatch, kind):
    """The data feed reads gs:// inputs IN PLACE through the storage
    seam's ranged reads (reference: HdfsAvroFileSplitReader.java:201
    fs.open — the cluster filesystem, no pre-copy): for every framing,
    every task's record stream over gs:// equals the local one."""
    from tony_tpu.storage import register_storage

    root = _fake_gcs(tmp_path, monkeypatch)
    try:
        local_dir = tmp_path / "data"
        local_dir.mkdir()
        if kind == "avro":
            rows = _avro_rows(97)
            paths = [_write_avro(local_dir, "a.avro", rows[:50],
                                 codec="snappy", block_records=7),
                     _write_avro(local_dir, "b.avro", rows[50:],
                                 block_records=11)]
            rs = None
        elif kind == "framed":
            from tony_tpu.io.framed import FramedWriter
            p = local_dir / "f.tony1"
            with FramedWriter(str(p), schema={"kind": "t"}) as w:
                for i in range(120):
                    w.append(f"rec-{i:04d}".encode())
            paths, rs = [str(p)], None
        elif kind == "lines":
            p = local_dir / "l.txt"
            p.write_bytes(b"".join(f"line-{i}\n".encode() for i in range(300)))
            paths, rs = [str(p)], 0
        else:
            p = local_dir / "x.bin"
            p.write_bytes(bytes(range(256)) * 32)
            paths, rs = [str(p)], 16

        # mirror the files into the fake bucket
        bucket = root / "bucket" / "ds"
        bucket.mkdir(parents=True)
        for lp in paths:
            (bucket / os.path.basename(lp)).write_bytes(
                open(lp, "rb").read())
        gs_paths = [f"gs://bucket/ds/{os.path.basename(lp)}" for lp in paths]

        for n in (1, 3):
            for idx in range(n):
                with FileSplitReader(paths, idx, n, record_size=rs) as r:
                    want = list(r)
                with FileSplitReader(gs_paths, idx, n, record_size=rs) as r:
                    assert not r.is_native
                    got = list(r)
                assert got == want, f"{kind} task {idx}/{n}"
    finally:
        register_storage("gs", None)


def test_gs_paths_reject_native_engine(tmp_path, monkeypatch):
    from tony_tpu.io.reader import DataFeedError
    from tony_tpu.storage import register_storage

    root = _fake_gcs(tmp_path, monkeypatch)
    try:
        (root / "bucket").mkdir()
        (root / "bucket" / "x.bin").write_bytes(b"\x00" * 64)
        with pytest.raises(DataFeedError, match="local files only"):
            FileSplitReader(["gs://bucket/x.bin"], record_size=16,
                            use_native=True)
    finally:
        register_storage("gs", None)


def test_convert_to_avro_roundtrip(tmp_path):
    """`tony convert --to avro --codec snappy`: JSONL records land as
    Avro 'bytes' datums in a spec-conformant container that the Avro arm
    of the data feed (and any Avro implementation) reads back
    payload-identically."""
    from tony_tpu.io import convert
    from tony_tpu.io.avro import read_datum, read_path_header

    src = tmp_path / "c.jsonl"
    rows = [json.dumps({"i": i, "t": "x" * (i % 11)}).encode()
            for i in range(200)]
    src.write_bytes(b"\n".join(rows) + b"\n")
    rc = convert.main([str(src), "--to", "avro", "--codec", "snappy",
                       "--out-dir", str(tmp_path / "out")])
    assert rc == 0
    out = str(tmp_path / "out" / "c.avro")
    hdr = read_path_header(out)
    got = []
    for n in (1, 3):
        per_task = []
        for idx in range(n):
            with FileSplitReader([out], idx, n) as r:
                for raw in r:
                    v, _ = read_datum(hdr.schema, memoryview(raw), 0)
                    per_task.append(v)
        assert per_task == rows, f"n={n}"
