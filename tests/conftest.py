"""Test harness config: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the framework's test
strategy (SURVEY.md §4: local multi-process/virtual-device backend + chaos env
hooks, mirroring the reference's MiniCluster in tony-mini), all sharding and
collective paths are exercised on ``--xla_force_host_platform_device_count=8``
CPU devices. Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TONY_TEST_MODE", "1")
