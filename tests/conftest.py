"""Test harness config: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the framework's test
strategy (SURVEY.md §4: local fake-cluster backend + chaos env hooks,
mirroring the reference's MiniCluster in tony-mini), all sharding and
collective paths are exercised on ``--xla_force_host_platform_device_count=8``
CPU devices.

The dev image's sitecustomize pre-imports jax at interpreter startup and pins
the TPU platform, making in-process env configuration too late — so
``pytest_configure`` re-execs pytest once with a clean environment (CPU
platform, 8 virtual devices, no sitecustomize on PYTHONPATH). Capture is
stopped first so the re-exec'd run inherits the real stdout/stderr.
"""

import os
import sys


def _clean_env() -> dict[str, str]:
    env = dict(os.environ)
    env["TONY_PYTEST_CLEAN"] = "1"
    env["TONY_TEST_MODE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p)
    return env


def pytest_configure(config):
    if os.environ.get("TONY_PYTEST_CLEAN") == "1":
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()   # restore real stdout/stderr fds
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + args,
              _clean_env())


os.environ.setdefault("TONY_TEST_MODE", "1")
