"""Test harness config: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the framework's test
strategy (SURVEY.md §4: local fake-cluster backend + chaos env hooks,
mirroring the reference's MiniCluster in tony-mini), all sharding and
collective paths are exercised on ``--xla_force_host_platform_device_count=8``
CPU devices.

The dev image's sitecustomize pre-imports jax at interpreter startup and pins
the TPU platform, making in-process env configuration too late — so
``pytest_configure`` re-execs pytest once with a clean environment (CPU
platform, 8 virtual devices, no sitecustomize on PYTHONPATH). Capture is
stopped first so the re-exec'd run inherits the real stdout/stderr.
"""

import os
import sys


def _clean_env() -> dict[str, str]:
    env = dict(os.environ)
    env["TONY_PYTEST_CLEAN"] = "1"
    env["TONY_TEST_MODE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p)
    return env


def _raise_stack_limit() -> None:
    """A full-suite process compiles 500+ XLA programs; deep LLVM
    recursion on the default 8 MB stack can segfault intermittently —
    raise the soft stack limit toward 256 MB (clamped to the hard cap)."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 256 * 1024 * 1024
        if hard != resource.RLIM_INFINITY:
            want = min(want, hard)
        if soft != resource.RLIM_INFINITY and soft < want:
            resource.setrlimit(resource.RLIMIT_STACK, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


def pytest_configure(config):
    _raise_stack_limit()     # both branches: the limit is inherited by
    #                          the re-exec and still applies without one
    if os.environ.get("TONY_PYTEST_CLEAN") == "1":
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()   # restore real stdout/stderr fds
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + args,
              _clean_env())


os.environ.setdefault("TONY_TEST_MODE", "1")


import pytest


@pytest.fixture
def retrace_guard():
    """Retrace-count regression guard for serving AND training programs.

    `tony_tpu.models.serve.TRACE_COUNTS` increments once per TRACE of a
    serving program, keyed by (program name, static shape) — a Python
    side effect inside the jitted bodies, so it counts compiles, not
    calls. `tony_tpu.models.train.TRACE_COUNTS` does the same for
    ``train_step``/``eval_step`` (keyed by batch leaf shapes). The
    fixture snapshots both counters and yields a guard whose
    ``new_traces(name)`` returns the per-shape trace deltas for one
    program and ``assert_max(name, n)`` pins an upper bound — the
    bucketed-admission invariant ("at most one program per length
    bucket") and the train-loop invariant ("one compiled step per batch
    shape across a full run_training run") are asserted through this,
    and any change that reintroduces retraces fails loudly here rather
    than as a silent latency regression."""

    def _trace_counts() -> dict:
        from tony_tpu.models import serve, train
        counts = dict(serve.TRACE_COUNTS)
        counts.update(train.TRACE_COUNTS)   # names disjoint by convention
        return counts

    before = _trace_counts()

    class Guard:
        def new_traces(self, name: str) -> dict:
            """{static shape: new traces} for program ``name`` since the
            fixture snapshot."""
            return {key[1]: count - before.get(key, 0)
                    for key, count in _trace_counts().items()
                    if key[0] == name and count > before.get(key, 0)}

        def total_new(self, name: str) -> int:
            return sum(self.new_traces(name).values())

        def assert_max(self, name: str, n: int) -> None:
            traces = self.new_traces(name)
            assert sum(traces.values()) <= n, (
                f"{name}: {sum(traces.values())} new traces (cap {n}) — "
                f"per-shape: {traces}")

    yield Guard()


@pytest.fixture(autouse=True)
def _forbid_codecs_in_exact_tests(request):
    """Bit-exactness tripwire: tests marked ``exact`` pin bit-identical
    numerics, where a stray quantized tensor channel would surface as an
    unexplainable flake. Arm the channel layer's guard for their
    duration — constructing any non-"none" codec sender/receiver then
    raises RuntimeError at the construction site instead."""
    if request.node.get_closest_marker("exact") is None:
        yield
        return
    from tony_tpu.channels import channel
    channel.forbid_codecs(True)
    try:
        yield
    finally:
        channel.forbid_codecs(False)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Reset XLA's in-process compilation caches after each test module.

    A full-suite process compiles 500+ XLA programs; with everything
    accumulated in one process the CPU compiler segfaults intermittently
    on a late compile (observed deterministically at the same test once
    the suite grew past ~520 programs, while the same tests pass in a
    fresh process). Dropping the caches at module boundaries keeps the
    compiler's working state bounded; modules re-jit their own programs
    anyway (shared cross-module jit hits are rare), so the runtime cost
    is small."""
    yield
    if "jax" in sys.modules:     # don't force a jax import on jax-free
        import jax               # modules just to clear empty caches
        jax.clear_caches()
