"""Test harness config: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the framework's test
strategy (SURVEY.md §4: local fake-cluster backend + chaos env hooks,
mirroring the reference's MiniCluster in tony-mini), all sharding and
collective paths are exercised on ``--xla_force_host_platform_device_count=8``
CPU devices.

The dev image's sitecustomize pre-imports jax at interpreter startup and pins
the TPU platform, making in-process env configuration too late — so
``pytest_configure`` re-execs pytest once with a clean environment (CPU
platform, 8 virtual devices, no sitecustomize on PYTHONPATH). Capture is
stopped first so the re-exec'd run inherits the real stdout/stderr.
"""

import os
import sys


def _clean_env() -> dict[str, str]:
    env = dict(os.environ)
    env["TONY_PYTEST_CLEAN"] = "1"
    env["TONY_TEST_MODE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p)
    return env


def _raise_stack_limit() -> None:
    """A full-suite process compiles 500+ XLA programs; deep LLVM
    recursion on the default 8 MB stack can segfault intermittently —
    raise the soft stack limit toward 256 MB (clamped to the hard cap)."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 256 * 1024 * 1024
        if hard != resource.RLIM_INFINITY:
            want = min(want, hard)
        if soft != resource.RLIM_INFINITY and soft < want:
            resource.setrlimit(resource.RLIMIT_STACK, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


def pytest_configure(config):
    _raise_stack_limit()     # both branches: the limit is inherited by
    #                          the re-exec and still applies without one
    if os.environ.get("TONY_PYTEST_CLEAN") == "1":
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()   # restore real stdout/stderr fds
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + args,
              _clean_env())


os.environ.setdefault("TONY_TEST_MODE", "1")


import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Reset XLA's in-process compilation caches after each test module.

    A full-suite process compiles 500+ XLA programs; with everything
    accumulated in one process the CPU compiler segfaults intermittently
    on a late compile (observed deterministically at the same test once
    the suite grew past ~520 programs, while the same tests pass in a
    fresh process). Dropping the caches at module boundaries keeps the
    compiler's working state bounded; modules re-jit their own programs
    anyway (shared cross-module jit hits are rare), so the runtime cost
    is small."""
    yield
    if "jax" in sys.modules:     # don't force a jax import on jax-free
        import jax               # modules just to clear empty caches
        jax.clear_caches()
