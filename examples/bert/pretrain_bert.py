"""BERT MLM pretraining — the 16-worker multi-host progression config.

BASELINE.json's final progression step: "16w BERT-base jax.distributed
multi-host". The framework boots ``jax.distributed`` across all hosts
(rt.initialize), every process feeds its shard of the global batch, and the
MLM loss/optimizer run as one SPMD program over the ``dp`` (or
``dp×fsdp``) mesh. Synthetic masked-token data (15% masked) keeps the
example self-contained.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=16 \
        --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/bert/pretrain_bert.py --steps 200 --config base'
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import tony_tpu.runtime as rt
from tony_tpu.io.prefetch import DevicePrefetcher
from tony_tpu.models import bert as B
from tony_tpu.models.loop import run_training
from tony_tpu.models.train import (batch_sharding, default_optimizer,
                                   init_state, make_train_step)
from tony_tpu.parallel import shard_pytree

CONFIGS = {"base": B.BERT_BASE, "tiny": B.BERT_TINY}
MASK_FRACTION = 0.15


def synthetic_mlm_batches(seed, batch, seq, cfg):
    """Infinite host-side MLM batches: random token ids with 15% positions
    masked-out as targets (-1 = ignore elsewhere), the MLM shape without a
    corpus. Numpy on the prefetcher's producer thread — masking/decode
    overlaps the device step."""
    rs = np.random.RandomState(seed)
    mask_id = cfg.vocab_size - 1
    while True:
        tokens = rs.randint(0, cfg.vocab_size,
                            size=(batch, seq)).astype(np.int32)
        masked = rs.rand(batch, seq) < MASK_FRACTION
        yield {
            "tokens": np.where(masked, mask_id, tokens).astype(np.int32),
            "targets": np.where(masked, tokens, -1).astype(np.int32),
        }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=16,
                        help="batch size PER PROCESS (global = this x hosts)")
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--lr", type=float, default=1e-4)
    args = parser.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] "
          f"{len(jax.devices())} global devices "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
          flush=True)

    cfg = CONFIGS[args.config]
    if jax.default_backend() != "tpu":
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    seq = min(args.seq_len, cfg.max_seq)

    params = shard_pytree(B.init_params(jax.random.PRNGKey(0), cfg),
                          B.logical_axes(cfg), mesh)
    opt = default_optimizer(lr=args.lr, total_steps=args.steps)
    state = init_state(params, opt)
    step = make_train_step(lambda p, b: B.mlm_loss(p, b, cfg, mesh), opt,
                           mesh)

    # Each process contributes its local shard; assembly + H2D run on the
    # prefetcher's producer thread, overlapped with the device step.
    data = DevicePrefetcher(
        synthetic_mlm_batches(1000 + info.task_index, args.batch_size,
                              seq, cfg),
        sharding=batch_sharding(mesh, logical=("batch", "seq")))
    t0 = time.perf_counter()

    def log_fn(i, metrics, batch):
        tok_s = (args.batch_size * info.num_processes * seq * (i + 1)
                 / (time.perf_counter() - t0))
        print(f"step {i} mlm loss {float(metrics['loss']):.4f} "
              f"tok/s {tok_s:,.0f}", flush=True)

    state, metrics = run_training(step, state, data, args.steps,
                                  log_every=20, log_fn=log_fn)
    loss = float(metrics["loss"]) if metrics else float("nan")
    ok = jnp.isfinite(loss)
    print(f"done: final loss {loss:.4f}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
