"""BERT MLM pretraining — the 16-worker multi-host progression config.

BASELINE.json's final progression step: "16w BERT-base jax.distributed
multi-host". The framework boots ``jax.distributed`` across all hosts
(rt.initialize), every process feeds its shard of the global batch, and the
MLM loss/optimizer run as one SPMD program over the ``dp`` (or
``dp×fsdp``) mesh. Synthetic masked-token data (15% masked) keeps the
example self-contained.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=16 \
        --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/bert/pretrain_bert.py --steps 200 --config base'
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time

import jax
import jax.numpy as jnp

import tony_tpu.runtime as rt
from tony_tpu.models import bert as B
from tony_tpu.models.train import (batch_sharding, default_optimizer,
                                   global_batch, init_state,
                                   make_train_step)
from tony_tpu.parallel import shard_pytree

CONFIGS = {"base": B.BERT_BASE, "tiny": B.BERT_TINY}
MASK_FRACTION = 0.15


def synthetic_mlm_batch(rng, batch, seq, cfg):
    """Random token ids with 15% positions masked-out as targets (-1 =
    ignore elsewhere), the MLM shape without a corpus."""
    kt, km = jax.random.split(rng)
    tokens = jax.random.randint(kt, (batch, seq), 0, cfg.vocab_size)
    masked = jax.random.uniform(km, (batch, seq)) < MASK_FRACTION
    targets = jnp.where(masked, tokens, -1)
    mask_id = cfg.vocab_size - 1
    inputs = jnp.where(masked, mask_id, tokens)
    return {"tokens": inputs, "targets": targets}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=16,
                        help="batch size PER PROCESS (global = this x hosts)")
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--lr", type=float, default=1e-4)
    args = parser.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] "
          f"{len(jax.devices())} global devices "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
          flush=True)

    cfg = CONFIGS[args.config]
    if jax.default_backend() != "tpu":
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    seq = min(args.seq_len, cfg.max_seq)

    params = shard_pytree(B.init_params(jax.random.PRNGKey(0), cfg),
                          B.logical_axes(cfg), mesh)
    opt = default_optimizer(lr=args.lr, total_steps=args.steps)
    state = init_state(params, opt)
    step = make_train_step(lambda p, b: B.mlm_loss(p, b, cfg, mesh), opt,
                           mesh)

    sharding = batch_sharding(mesh, logical=("batch", "seq"))
    rng = jax.random.PRNGKey(1000 + info.task_index)
    loss = float("nan")
    t0 = time.perf_counter()
    for i in range(args.steps):
        rng, key = jax.random.split(rng)
        # Each process contributes its local shard of the global batch.
        batch = global_batch(
            sharding, synthetic_mlm_batch(key, args.batch_size, seq, cfg))
        state, metrics = step(state, batch)
        if i % 20 == 0 or i == args.steps - 1:
            loss = float(metrics["loss"])
            tok_s = (args.batch_size * info.num_processes * seq * (i + 1)
                     / (time.perf_counter() - t0))
            print(f"step {i} mlm loss {loss:.4f} tok/s {tok_s:,.0f}",
                  flush=True)
    ok = jnp.isfinite(loss)
    print(f"done: final loss {loss:.4f}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
