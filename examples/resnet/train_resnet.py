"""ResNet-50 data-parallel training — the 8-worker progression config.

BASELINE.json progression step 4: "8w ResNet-50 DP". One SPMD program over
the ``dp`` mesh axis: every process contributes its local image shard to a
global batch, XLA inserts the gradient all-reduce, and batch-norm statistics
are cross-replica-synced by construction (the stats come out of the same
compiled program). Synthetic ImageNet-shaped data keeps the example
dependency-free; the data-feed layer (tony_tpu.io) plugs in for real input.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=8 \
        --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/resnet/train_resnet.py --steps 100'
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import tony_tpu.runtime as rt
from tony_tpu.io.prefetch import DevicePrefetcher
from tony_tpu.models import resnet as R
from tony_tpu.models.loop import run_training
from tony_tpu.models.train import batch_sharding


def synthetic_batches(seed, batch, image_size, num_classes):
    """Infinite host-side image batches (f32 numpy; the train step casts
    to the model dtype on device — a fused elementwise op)."""
    rs = np.random.RandomState(seed)
    while True:
        yield {
            "image": rs.randn(batch, image_size, image_size, 3)
                       .astype(np.float32),
            "label": rs.randint(0, num_classes, size=(batch,))
                       .astype(np.int32),
        }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--depth", type=int, default=50,
                        choices=sorted(R.STAGE_SIZES))
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch_size", type=int, default=32,
                        help="batch size PER PROCESS (global = this x hosts)")
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--num_classes", type=int, default=1000)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] devices={len(jax.devices())} "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
          flush=True)

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    params, stats = R.init_resnet(jax.random.PRNGKey(0), depth=args.depth,
                                  num_classes=args.num_classes, dtype=dtype)
    opt = optax.sgd(args.lr, momentum=0.9, nesterov=True)
    # batch-norm stats ride in the state pytree, so the step keeps the
    # (state, batch) -> (state, metrics) shape run_training drives
    state = {"params": params, "stats": stats,
             "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}

    def step_impl(state, batch):
        batch = dict(batch, image=batch["image"].astype(dtype))
        (loss, new_stats), grads = jax.value_and_grad(
            R.classification_loss, has_aux=True)(
                state["params"], state["stats"], batch, args.depth)
        updates, opt_state = opt.update(grads, state["opt_state"],
                                        state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "stats": new_stats,
                "opt_state": opt_state,
                "step": state["step"] + 1}, {"loss": loss}

    jitted = jax.jit(step_impl, donate_argnums=(0,))

    def step_fn(state, batch):
        with jax.set_mesh(mesh):
            return jitted(state, batch)

    # Per-process shard → global array, assembled + transferred on the
    # prefetcher's producer thread (multi-host feeding pattern, off the
    # step critical path).
    data = DevicePrefetcher(
        synthetic_batches(info.task_index, args.batch_size,
                          args.image_size, args.num_classes),
        sharding=batch_sharding(mesh))
    t0 = time.perf_counter()

    def log_fn(i, metrics, batch):
        img_s = (args.batch_size * info.num_processes * (i + 1)
                 / (time.perf_counter() - t0))
        print(f"step {i} loss {float(metrics['loss']):.4f} "
              f"images/s {img_s:,.1f}", flush=True)

    state, metrics = run_training(step_fn, state, data, args.steps,
                                  log_every=10, log_fn=log_fn)
    loss = float(metrics["loss"]) if metrics else float("nan")
    ok = jnp.isfinite(loss)
    print(f"done: final loss {loss:.4f}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
