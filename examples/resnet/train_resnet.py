"""ResNet-50 data-parallel training — the 8-worker progression config.

BASELINE.json progression step 4: "8w ResNet-50 DP". One SPMD program over
the ``dp`` mesh axis: every process contributes its local image shard to a
global batch, XLA inserts the gradient all-reduce, and batch-norm statistics
are cross-replica-synced by construction (the stats come out of the same
compiled program). Synthetic ImageNet-shaped data keeps the example
dependency-free; the data-feed layer (tony_tpu.io) plugs in for real input.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=8 \
        --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/resnet/train_resnet.py --steps 100'
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import optax

import tony_tpu.runtime as rt
from tony_tpu.models import resnet as R
from tony_tpu.models.train import batch_sharding, global_batch


def synthetic_batch(rng, batch, image_size, num_classes, dtype):
    kx, ky = jax.random.split(rng)
    return {
        "image": jax.random.normal(
            kx, (batch, image_size, image_size, 3), dtype),
        "label": jax.random.randint(ky, (batch,), 0, num_classes),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--depth", type=int, default=50,
                        choices=sorted(R.STAGE_SIZES))
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch_size", type=int, default=32,
                        help="batch size PER PROCESS (global = this x hosts)")
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--num_classes", type=int, default=1000)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] devices={len(jax.devices())} "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}",
          flush=True)

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    params, stats = R.init_resnet(jax.random.PRNGKey(0), depth=args.depth,
                                  num_classes=args.num_classes, dtype=dtype)
    opt = optax.sgd(args.lr, momentum=0.9, nesterov=True)
    opt_state = opt.init(params)

    def step_fn(params, stats, opt_state, batch):
        (loss, new_stats), grads = jax.value_and_grad(
            R.classification_loss, has_aux=True)(
                params, stats, batch, args.depth)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    jitted = jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def step(params, stats, opt_state, batch):
        with jax.set_mesh(mesh):
            return jitted(params, stats, opt_state, batch)

    sharding = batch_sharding(mesh)
    rng = jax.random.PRNGKey(info.task_index)
    loss = float("nan")
    t0 = time.perf_counter()
    for i in range(args.steps):
        rng, key = jax.random.split(rng)
        # Per-process shard → global array (multi-host feeding pattern).
        batch = global_batch(
            sharding, synthetic_batch(key, args.batch_size, args.image_size,
                                      args.num_classes, dtype))
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
        if i % 10 == 0 or i == args.steps - 1:
            loss = float(loss)
            img_s = (args.batch_size * info.num_processes * (i + 1)
                     / (time.perf_counter() - t0))
            print(f"step {i} loss {loss:.4f} images/s {img_s:,.1f}",
                  flush=True)
    ok = jnp.isfinite(loss)
    print(f"done: final loss {loss:.4f}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
