"""Distributed MNIST in JAX — the north-star example.

TPU-native port of the reference's MNIST recipes (reference: tony-examples/
mnist-tensorflow/mnist_distributed.py:190-227 — TF1 PS/worker with
MonitoredTrainingSession — and tony-examples/mnist-pytorch/
mnist_distributed.py:113-226 — manual all-reduce). Both patterns collapse
into one SPMD program: ``tony_tpu.runtime`` bootstraps ``jax.distributed``
from the coordinator-exported env, every process contributes its local batch
shard to a global ``jax.Array``, and XLA inserts the gradient all-reduce from
the sharding annotations — there is no PS, no explicit ``all_reduce`` call,
and no TF_CONFIG parsing.

Runs unchanged on: a TPU pod slice (one process per host), multi-process CPU
(the E2E fake cluster), or a single process. Data is synthetic-MNIST (28x28
class-conditioned patterns) so the example has zero download dependencies;
pass --data_dir with the real IDX files to train on true MNIST.

Usage (via the framework):
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=2 --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/mnist/mnist_distributed.py --steps 100'
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys
import time

import numpy as np

import tony_tpu.runtime as rt


def synthetic_mnist(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Class-conditioned synthetic digits: each class c gets a fixed random
    28x28 template; samples are noisy templates. Learnable to ~100% by a
    small MLP, shaped exactly like MNIST."""
    rng = np.random.RandomState(seed)
    templates = np.random.RandomState(0).rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=(n,)).astype(np.int32)
    images = templates[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return images, labels


def load_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        return (np.frombuffer(f.read(), dtype=np.uint8)
                .reshape(n, rows, cols).astype(np.float32) / 255.0)


def load_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch_size", type=int, default=256,
                        help="GLOBAL batch size")
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--data_dir", default="",
                        help="dir with train-images-idx3-ubyte.gz etc.; "
                             "synthetic data when unset")
    parser.add_argument("--target_acc", type=float, default=0.95)
    args = parser.parse_args()

    # --- tony bootstrap (the TF_CONFIG-parsing replacement) ---------------
    info = rt.initialize()
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = rt.mesh()            # axes from tony.application.mesh; default dp
    dp_axis = mesh.axis_names[0]
    print(f"[{info.job_name}:{info.task_index}] process {info.process_id}/"
          f"{info.num_processes}, {len(jax.devices())} global devices, "
          f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}", flush=True)

    # --- data -------------------------------------------------------------
    if args.data_dir:
        images = load_idx_images(os.path.join(
            args.data_dir, "train-images-idx3-ubyte.gz"))
        labels = load_idx_labels(os.path.join(
            args.data_dir, "train-labels-idx1-ubyte.gz"))
    else:
        images, labels = synthetic_mnist(60000, seed=info.process_id)

    # --- model: 2-layer MLP, pure functions -------------------------------
    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (784, args.hidden)) * 0.05,
            "b1": jnp.zeros((args.hidden,)),
            "w2": jax.random.normal(k2, (args.hidden, 10)) * 0.05,
            "b2": jnp.zeros((10,)),
        }

    def forward(params, x):
        h = jnp.maximum(x @ params["w1"] + params["b1"], 0.0)
        return h @ params["w2"] + params["b2"]

    def loss_fn(params, batch):
        logits = forward(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    tx = optax.sgd(args.lr, momentum=0.9)

    # --- sharding: batch over dp, params replicated ------------------------
    from tony_tpu.io.prefetch import DevicePrefetcher
    from tony_tpu.models.loop import run_training
    from tony_tpu.models.train import init_state, make_train_step

    repl = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P(dp_axis))
    params = jax.device_put(
        init_params(jax.random.PRNGKey(info.session_id)), repl)
    state = init_state(params, tx)
    train_step = make_train_step(loss_fn, tx, mesh)

    @jax.jit
    def accuracy(params, batch):
        return (forward(params, batch["x"]).argmax(-1) == batch["y"]).mean()

    # Each process feeds its slice of the global batch; the prefetcher's
    # producer thread runs the index/gather/reshape decode AND the
    # jax.make_array_from_process_local_data assembly (the
    # HdfsAvroFileSplitReader byte-split idea applied to arrays) while the
    # device runs the previous step.
    local_bs = args.batch_size // info.num_processes
    rng = np.random.RandomState(1234 + info.process_id)

    def host_batches():
        while True:
            idx = rng.randint(0, len(images), size=(local_bs,))
            yield {"x": images[idx].reshape(local_bs, 784), "y": labels[idx]}

    # held-out global batch for the periodic eval hook
    eval_batch = {
        k: jax.make_array_from_process_local_data(batch_sharded, v)
        for k, v in next(host_batches()).items()}

    t0 = time.time()

    def log_fn(step, metrics, batch):
        if info.process_id == 0:
            eval_s = (f" acc {float(metrics['eval']):.3f}"
                      if "eval" in metrics else "")
            print(f"step {step} loss {float(metrics['loss']):.4f}{eval_s}",
                  flush=True)

    state, metrics = run_training(
        train_step, state,
        DevicePrefetcher(host_batches(), sharding=batch_sharded),
        args.steps,
        eval_fn=lambda s: accuracy(s["params"], eval_batch),
        eval_every=50, log_every=50, log_fn=log_fn)
    wall = time.time() - t0

    loss = float(metrics["loss"]) if metrics else float("nan")
    acc = float(accuracy(state["params"], eval_batch))
    throughput = args.steps * args.batch_size / wall
    if info.process_id == 0:
        print(f"done: {args.steps} steps in {wall:.1f}s "
              f"({throughput:.0f} img/s), final loss {loss:.4f}, "
              f"acc {acc:.3f}", flush=True)
    if acc < args.target_acc:
        print(f"FAILED: accuracy {acc:.3f} < target {args.target_acc}",
              file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
