"""Distributed MNIST in JAX — the north-star example.

TPU-native port of the reference's MNIST recipes (reference: tony-examples/
mnist-tensorflow/mnist_distributed.py:190-227 — TF1 PS/worker with
MonitoredTrainingSession — and tony-examples/mnist-pytorch/
mnist_distributed.py:113-226 — manual all-reduce). Both patterns collapse
into one SPMD program: ``tony_tpu.runtime`` bootstraps ``jax.distributed``
from the coordinator-exported env, every process contributes its local batch
shard to a global ``jax.Array``, and XLA inserts the gradient all-reduce from
the sharding annotations — there is no PS, no explicit ``all_reduce`` call,
and no TF_CONFIG parsing.

Runs unchanged on: a TPU pod slice (one process per host), multi-process CPU
(the E2E fake cluster), or a single process. Data is synthetic-MNIST (28x28
class-conditioned patterns) so the example has zero download dependencies;
pass --data_dir with the real IDX files to train on true MNIST.

Usage (via the framework):
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=2 --conf tony.application.mesh=dp=-1 \
        --src_dir examples \
        --executes 'python examples/mnist/mnist_distributed.py --steps 100'
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys
import time

import numpy as np

import tony_tpu.runtime as rt


def synthetic_mnist(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Class-conditioned synthetic digits: each class c gets a fixed random
    28x28 template; samples are noisy templates. Learnable to ~100% by a
    small MLP, shaped exactly like MNIST."""
    rng = np.random.RandomState(seed)
    templates = np.random.RandomState(0).rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=(n,)).astype(np.int32)
    images = templates[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return images, labels


def load_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        return (np.frombuffer(f.read(), dtype=np.uint8)
                .reshape(n, rows, cols).astype(np.float32) / 255.0)


def load_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch_size", type=int, default=256,
                        help="GLOBAL batch size")
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--data_dir", default="",
                        help="dir with train-images-idx3-ubyte.gz etc.; "
                             "synthetic data when unset")
    parser.add_argument("--target_acc", type=float, default=0.95)
    args = parser.parse_args()

    # --- tony bootstrap (the TF_CONFIG-parsing replacement) ---------------
    info = rt.initialize()
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = rt.mesh()            # axes from tony.application.mesh; default dp
    dp_axis = mesh.axis_names[0]
    print(f"[{info.job_name}:{info.task_index}] process {info.process_id}/"
          f"{info.num_processes}, {len(jax.devices())} global devices, "
          f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}", flush=True)

    # --- data -------------------------------------------------------------
    if args.data_dir:
        images = load_idx_images(os.path.join(
            args.data_dir, "train-images-idx3-ubyte.gz"))
        labels = load_idx_labels(os.path.join(
            args.data_dir, "train-labels-idx1-ubyte.gz"))
    else:
        images, labels = synthetic_mnist(60000, seed=info.process_id)

    # --- model: 2-layer MLP, pure functions -------------------------------
    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (784, args.hidden)) * 0.05,
            "b1": jnp.zeros((args.hidden,)),
            "w2": jax.random.normal(k2, (args.hidden, 10)) * 0.05,
            "b2": jnp.zeros((10,)),
        }

    def forward(params, x):
        h = jnp.maximum(x @ params["w1"] + params["b1"], 0.0)
        return h @ params["w2"] + params["b2"]

    def loss_fn(params, x, y):
        logits = forward(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    tx = optax.sgd(args.lr, momentum=0.9)

    # --- sharding: batch over dp, params replicated ------------------------
    repl = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P(dp_axis))
    params = jax.device_put(
        init_params(jax.random.PRNGKey(info.session_id)), repl)
    opt_state = jax.device_put(tx.init(params), repl)

    @jax.jit
    def train_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def accuracy(params, x, y):
        return (forward(params, x).argmax(-1) == y).mean()

    # Each process feeds its slice of the global batch
    # (jax.make_array_from_process_local_data — the HdfsAvroFileSplitReader
    # byte-split idea applied to arrays).
    local_bs = args.batch_size // info.num_processes
    rng = np.random.RandomState(1234 + info.process_id)

    def global_batch():
        idx = rng.randint(0, len(images), size=(local_bs,))
        x = images[idx].reshape(local_bs, 784)
        y = labels[idx]
        gx = jax.make_array_from_process_local_data(batch_sharded, x)
        gy = jax.make_array_from_process_local_data(batch_sharded, y)
        return gx, gy

    t0 = time.time()
    loss = float("nan")
    for step in range(args.steps):
        x, y = global_batch()
        params, opt_state, loss = train_step(params, opt_state, x, y)
        if step % 50 == 0 and info.process_id == 0:
            print(f"step {step} loss {float(loss):.4f}", flush=True)
    wall = time.time() - t0

    x, y = global_batch()
    acc = float(accuracy(params, x, y))
    throughput = args.steps * args.batch_size / wall
    if info.process_id == 0:
        print(f"done: {args.steps} steps in {wall:.1f}s "
              f"({throughput:.0f} img/s), final loss {float(loss):.4f}, "
              f"acc {acc:.3f}", flush=True)
    if acc < args.target_acc:
        print(f"FAILED: accuracy {acc:.3f} < target {args.target_acc}",
              file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
