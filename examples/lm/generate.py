"""Text generation from a trained LM checkpoint — the inference half of
examples/lm/train_lm.py.

Loads the orbax checkpoint written by train_lm.py and decodes with the
KV-cache path (prefill + scan-decode, one compiled program). Runs on TPU
(flash-attention prefill) or CPU.

Usage:
    python examples/lm/generate.py --ckpt_dir /tmp/lm-ckpt --preset tiny \
        --max_new_tokens 64 --temperature 0.8
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp

from tony_tpu.models import transformer as T
from tony_tpu.models.checkpoint import CheckpointManager
from tony_tpu.models.decode import generate


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny", choices=sorted(T.PRESETS))
    parser.add_argument("--ckpt_dir", default="",
                        help="orbax checkpoint dir (empty = random params)")
    parser.add_argument("--batch_size", type=int, default=2)
    parser.add_argument("--prompt_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--top_k", type=int, default=40)
    parser.add_argument("--top_p", type=float, default=0.0,
                        help="nucleus sampling mass (0 = off)")
    parser.add_argument("--beam_width", type=int, default=0,
                        help="beam search instead of sampling (> 0 "
                             "enables; returns the best beam)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    cfg = T.PRESETS[args.preset].scaled(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    if args.ckpt_dir:
        with CheckpointManager(args.ckpt_dir) as mgr:
            from tony_tpu.models.train import default_optimizer, init_state
            state = mgr.restore(
                template=init_state(params, default_optimizer()))
        params = state["params"]
        print(f"restored step {int(state['step'])} from {args.ckpt_dir}")

    rng = jax.random.PRNGKey(args.seed)
    prompt = jax.random.randint(rng, (args.batch_size, args.prompt_len), 0,
                                cfg.vocab_size)
    t0 = time.perf_counter()
    if args.beam_width > 0:
        from tony_tpu.models.decode import beam_search
        beams = beam_search(params, prompt, cfg,
                            max_new_tokens=args.max_new_tokens,
                            beam_width=args.beam_width)
        int(beams.tokens[0, 0, -1])
        n = int(beams.tokens.shape[0] * args.max_new_tokens)
        dt = time.perf_counter() - t0
        print(f"beam search W={args.beam_width}: best-beam shape "
              f"{beams.tokens.shape[::2]} in {dt:.2f}s "
              f"({n / dt:,.0f} tok/s incl. compile)")
        print("best beam token ids:",
              beams.tokens[0, 0, args.prompt_len:].tolist()[:16])
        print("beam scores:", [round(float(x), 2) for x in beams.scores[0]])
        return 0
    out = generate(params, prompt, cfg, max_new_tokens=args.max_new_tokens,
                   rng=rng, temperature=args.temperature, top_k=args.top_k,
                   top_p=args.top_p)
    int(out.tokens[0, -1])   # host fetch: timing must include execution
    n = int(out.tokens.shape[0] * args.max_new_tokens)
    dt = time.perf_counter() - t0
    print(f"generated {out.tokens.shape} in {dt:.2f}s "
          f"({n / dt:,.0f} tok/s incl. compile)")
    print("sample token ids:", out.tokens[0, args.prompt_len:].tolist()[:16])
    print("mean logprob:", float(out.logprobs.mean()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
