"""Flagship decoder-LM training: sharded, checkpointed, profiled, retry-safe.

The full TPU-native training recipe the framework exists to orchestrate —
everything the reference left to user scripts, done the jax way:

- ``tony_tpu.runtime`` bootstraps jax.distributed from the coordinator env
  and builds the device mesh from ``tony.application.mesh``;
- params are sharded by logical-axis rules (dp/fsdp/tp/cp) and the train
  step compiles to one SPMD program per step (XLA inserts the collectives);
- orbax checkpointing with ``restore_or_init`` makes coordinator retries
  (ATTEMPT_NUMBER > 0) resume from the last step instead of restarting;
- the input pipeline is device-prefetched (``tony_tpu.io.prefetch``):
  reader decode, global-array assembly, and the H2D copy run on a
  producer thread, overlapped with device compute by the framework's
  ``run_training`` driver (``--prefetch_depth 0`` for the synchronous
  contrast);
- step-bounded profiler capture (``tony.task.profile.enabled=true``) records
  steady-state traces, skipping compile noise.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.worker.instances=4 \
        --conf tony.application.mesh=dp=-1 \
        --conf tony.am.retry-count=2 \
        --src_dir examples \
        --executes 'python examples/lm/train_lm.py --steps 200 \
                    --ckpt_dir /tmp/lm-ckpt --preset small'
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import tony_tpu.runtime as rt
from tony_tpu.io.prefetch import (DevicePrefetcher, elastic_epochs,
                                   reader_epochs, synchronous_batches)
from tony_tpu.models import transformer as T
from tony_tpu.models.checkpoint import CheckpointManager, attempt_number
from tony_tpu.models.loop import GangLostError, run_training
from tony_tpu.models.train import (batch_sharding, data_parallel_rank,
                                   default_optimizer, init_state,
                                   make_train_step)
from tony_tpu.parallel import shard_pytree
from tony_tpu.runtime.profiler import StepTracer


def synthetic_source(seed: int, batch: int, seq: int, vocab: int):
    """Infinite host-side token batches (numpy: the prefetcher's producer
    thread decodes + assembles while the device computes)."""
    rs = np.random.RandomState(seed)
    while True:
        tokens = rs.randint(0, vocab, size=(batch, seq + 1)).astype(np.int32)
        yield {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}


def elastic_file_source(paths, global_batch: int, seq: int, seed: int,
                        start_step: int):
    """World-size-invariant feed for ELASTIC jobs (tony.elastic.enabled):
    the canonical single-reader stream is sliced per process, so the
    global batch at step s is identical before and after a shrink/regrow
    and the resumed loss curve continues exactly where the checkpoint
    left it (tony_tpu.io.prefetch.elastic_epochs; tradeoff: every process
    reads the whole dataset)."""
    rows, per_epoch = elastic_epochs(paths, global_batch, np.int32,
                                     (seq + 1,), shuffle=True, seed=seed,
                                     start_step=start_step)

    def batches():
        for tokens in rows:
            yield {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}

    return batches()


def file_source(paths, batch: int, seq: int, seed: int):
    """Epochal host-batch source over the sharded data-feed layer: each
    record is seq+1 int32 token ids; every process reads only its
    byte-range split (tony_tpu.io), reshuffled deterministically per epoch
    (seed + epoch). The DevicePrefetcher cycles epochs until the step loop
    stops pulling."""
    epoch_fn, per_epoch = reader_epochs(paths, batch, np.int32, (seq + 1,),
                                        shuffle=True, seed=seed)
    if per_epoch == 0:
        raise ValueError(
            f"data files hold fewer than one full batch per process "
            f"(batch_size={batch}, seq_len={seq}) — nothing to train on")

    def epochs(epoch: int):
        for tokens in epoch_fn(epoch):
            yield {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}

    return epochs


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny",
                        choices=sorted(T.PRESETS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=8,
                        help="batch size PER PROCESS (global = this x hosts)")
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--ckpt_dir", default="")
    parser.add_argument("--ckpt_every", type=int, default=50)
    parser.add_argument("--data_files", nargs="*", default=[],
                        help="binary token files (records of seq_len+1 "
                             "int32 ids) fed via the sharded data layer; "
                             "empty = synthetic data")
    parser.add_argument("--cp_strategy", default="ring",
                        choices=("ring", "ulysses"),
                        help="context-parallel attention when the mesh has "
                             "a cp axis: ring (ppermute K/V rotation) or "
                             "ulysses (all-to-all head resharding)")
    parser.add_argument("--num_experts", type=int, default=0,
                        help="mixture-of-experts FFN with this many experts "
                             "(0 = dense); experts shard over the mesh's ep "
                             "axis, composing with dp/tp/cp/pp")
    parser.add_argument("--pp_schedule", default="gpipe",
                        choices=("gpipe", "1f1b"),
                        help="pipeline schedule when the mesh has a pp "
                             "axis: gpipe (default) or 1f1b (O(pp) live "
                             "microbatch activations instead of O(M) — "
                             "for deep pipelines / many microbatches)")
    parser.add_argument("--attn_window", type=int, default=0,
                        help="sliding-window attention: each token "
                             "attends its N most recent positions "
                             "(0 = full causal); attention cost goes "
                             "O(seq*window) instead of O(seq^2)")
    parser.add_argument("--elastic_data", type=int, default=0,
                        metavar="GLOBAL_BATCH",
                        help="feed --data_files through the world-size-"
                             "invariant elastic source with this FIXED "
                             "global batch (must divide evenly over every "
                             "world size the job can shrink to; each "
                             "process feeds global/N rows) — required for "
                             "loss-curve continuity under "
                             "tony.elastic.enabled shrink/regrow. The "
                             "value is deliberately explicit: deriving it "
                             "from the live process count would change "
                             "the canonical stream across the very "
                             "transitions it exists to survive")
    parser.add_argument("--prefetch_depth", type=int, default=2,
                        help="device-prefetch queue depth (batches decoded "
                             "+ transferred ahead of the step loop); 0 = "
                             "synchronous inline feed (A/B contrast)")
    args = parser.parse_args()

    info = rt.initialize()
    mesh = rt.mesh()
    print(f"[{info.job_name}:{info.task_index}] attempt={info.attempt} "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"devices={len(jax.devices())}", flush=True)

    on_tpu = jax.default_backend() == "tpu"
    cfg = T.PRESETS[args.preset].scaled(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        cp_strategy=args.cp_strategy,
        num_experts=args.num_experts,
        pp_schedule=args.pp_schedule,
        attn_window=args.attn_window)

    params = shard_pytree(T.init_params(jax.random.PRNGKey(0), cfg),
                          T.logical_axes(cfg), mesh)
    opt = default_optimizer(lr=args.lr, total_steps=args.steps)
    use_1f1b = cfg.pp_schedule == "1f1b" and mesh.shape.get("pp", 1) > 1
    print(f"pipeline schedule: {'1f1b' if use_1f1b else 'gpipe'}",
          flush=True)
    if use_1f1b:
        # 1F1B produces its own gradients (the loss head runs inside the
        # pipeline) — it plugs in through the value_and_grad hook
        step_fn = make_train_step(
            None, opt, mesh,
            value_and_grad_fn=lambda p, b: T.lm_value_and_grad(
                p, b, cfg, mesh))
    else:
        step_fn = make_train_step(lambda p, b: T.lm_loss(p, b, cfg, mesh),
                                  opt, mesh)

    mgr = (CheckpointManager(args.ckpt_dir,
                             save_interval_steps=args.ckpt_every)
           if args.ckpt_dir else None)
    state = (mgr.restore_or_init(lambda: init_state(params, opt))
             if mgr else init_state(params, opt))
    start_step = int(state["step"])

    b_sharding = batch_sharding(mesh, logical=("batch", "seq"))
    tracer = StepTracer(start=start_step + 5, stop=start_step + 8)

    # Host-batch source: files (epochal, per-epoch reshuffle) or synthetic.
    # Synthetic seeds by dp-rank, not task index: on meshes where the batch
    # replicates across processes (pure pp/tp) every process must feed
    # identical data. Each process contributes its LOCAL shard; the
    # prefetcher assembles global sharded arrays on its producer thread so
    # decode + H2D overlap device compute.
    if args.elastic_data:
        if not args.data_files:
            raise SystemExit("--elastic_data requires --data_files")
        source = elastic_file_source(
            args.data_files, args.elastic_data,
            args.seq_len, seed=0, start_step=start_step)
    elif args.data_files:
        source = file_source(args.data_files, args.batch_size,
                             args.seq_len, seed=attempt_number())
    else:
        source = synthetic_source(data_parallel_rank(mesh)
                                  + 1000 * attempt_number(),
                                  args.batch_size, args.seq_len,
                                  cfg.vocab_size)
    if args.prefetch_depth > 0:
        data = DevicePrefetcher(source, sharding=b_sharding,
                                depth=args.prefetch_depth)
    else:
        # synchronous contrast: decode + assembly inline on the step path
        # (same source protocol, no overlap)
        data = synchronous_batches(source, sharding=b_sharding)

    t0 = time.perf_counter()

    def log_fn(step, metrics, batch):
        loss = float(metrics["loss"])
        # global tokens/step from the assembled batch itself (batch may
        # shard over processes — dp — or replicate — pure pp/tp)
        gb = batch["inputs"].shape[0]
        tok_s = (gb * args.seq_len * (step - start_step + 1)
                 / (time.perf_counter() - t0))
        print(f"step {step} loss {loss:.4f} tok/s {tok_s:,.0f}", flush=True)

    try:
        state, metrics = run_training(
            step_fn, state, data, args.steps, start_step=start_step,
            checkpoint=mgr, log_every=20, log_fn=log_fn,
            step_hook=tracer.step)
    except GangLostError as e:
        # elastic contract: the executor holds this distinguished exit and
        # relaunches us against the resized gang (checkpoints are flushed)
        print(f"gang lost: {e}", flush=True)
        return e.exit_code
    finally:
        tracer.close()
    if mgr:
        mgr.close()
    loss = float(metrics["loss"]) if metrics else float("nan")
    ok = jnp.isfinite(loss)
    print(f"done: final loss {loss:.4f}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
