"""Cross-slice MPMD pipeline-parallel training: one stage gang's program.

The per-gang PROGRAM of a pipeline job (``tony.pipeline.stages`` +
``tony.{job}.program``): every stage gang runs THIS script; the stage it
plays, how many stages exist, and where its neighbor gangs' tensor-
channel hubs listen all arrive through the executor environment
(``TONY_PIPELINE_*`` / ``TONY_CHANNEL_*``), exported from the
coordinator's channel registry at gang-barrier release.

The model is a compact residual-MLP LM stand-in split layer-wise across
stages — stage s holds stage s's block params, the LAST stage holds the
loss head — sized so the tier-1 e2e suite can train it across two local
gangs in seconds. Per step, every stage runs its share of the
cross-slice 1F1B schedule (:class:`tony_tpu.parallel.pipeline
.CrossSlicePipeline`): activations stream to stage+1 and cotangents back
to stage-1 over DCN channels while the local device computes the
adjacent microbatches. Losses/params land in ``--out`` as an npz so the
harness can pin them bit-identical to the in-slice
``pipeline_value_and_grad`` schedule on the same params and batches.

Submit shape (stage gangs are ordinary job types)::

    tony submit \
      --conf tony.stage0.instances=1 --conf tony.stage1.instances=1 \
      --conf tony.pipeline.stages=stage0,stage1 \
      --conf tony.stage0.program='python examples/lm/train_pipeline.py ...' \
      --conf tony.stage1.program='python examples/lm/train_pipeline.py ...' \
      --executes 'python examples/lm/train_pipeline.py'
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.channels import open_stage_links, stage_env
from tony_tpu.models.loop import run_training
from tony_tpu.parallel.pipeline import CrossSlicePipeline


def stage_fn(p, x):
    """One stage's block: residual tanh MLP, shape-preserving (the
    pipeline stage contract)."""
    return x + jnp.tanh(x @ p["w"] + p["b"])


def loss_head(hp, out, tgt):
    """Mean-squared regression head — the per-microbatch scalar the last
    stage seeds its backward from."""
    return jnp.mean((out @ hp["wo"] - tgt) ** 2)


def init_stage_params(stage: int, dim: int, seed: int = 0):
    """Deterministic per-stage block params: seeded by (seed, stage), so
    the in-slice reference can rebuild the full stacked tree."""
    rs = np.random.RandomState(seed * 1000 + stage)
    return {
        "w": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.3),
        "b": jnp.asarray(rs.randn(dim).astype(np.float32) * 0.1),
    }


def init_head_params(dim: int, seed: int = 0):
    rs = np.random.RandomState(seed * 1000 + 999)
    return {"wo": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.2)}


def batch_for(step: int, m: int, mb: int, dim: int, seed: int = 0):
    """(inputs [M, mb, dim], targets [M, mb, dim]) for one step — pure
    function of (seed, step): stage 0 feeds the inputs, the last stage
    the targets, and the reference harness reproduces both."""
    rs = np.random.RandomState(seed * 100_000 + step)
    x = rs.randn(m, mb, dim).astype(np.float32)
    tgt = rs.randn(m, mb, dim).astype(np.float32)
    return x, tgt


def sgd(params, grads, lr: float):
    return jax.tree.map(lambda p, g: p - lr * g, params, grads)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="train_pipeline")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--mb_rows", type=int, default=4)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--window", type=int, default=8)
    ap.add_argument("--interleave", type=int, default=0,
                    help="virtual stages per gang (0 = take "
                    "TONY_PIPELINE_INTERLEAVE from the coordinator)")
    ap.add_argument("--channel_compression", default="",
                    choices=("", "none", "bf16", "int8"),
                    help="wire codec for the tensor channels ('' = take "
                    "TONY_CHANNEL_COMPRESSION from the coordinator; all "
                    "stage gangs must pass the same value)")
    ap.add_argument("--out", default="", help="npz with losses + final "
                    "params (filename gains a -stage<k> suffix)")
    args = ap.parse_args(argv)

    env = stage_env()
    if env is None:
        print("train_pipeline.py must run as a pipeline stage "
              "(tony.pipeline.stages): no TONY_PIPELINE_STAGE in env",
              file=sys.stderr)
        return 2
    if args.interleave > 0:
        env["interleave"] = args.interleave
    if args.channel_compression:
        env["compression"] = args.channel_compression
    links = open_stage_links(window=args.window, **env)
    m, mb, dim = args.microbatches, args.mb_rows, args.dim
    v = links.interleave
    # chunk j's block is VIRTUAL stage j*S + s of the model — the same
    # seeding the in-slice reference uses for its stacked stage axis
    params = init_stage_params(links.stage, dim, args.seed) if v == 1 \
        else [init_stage_params(links.global_stage(j), dim, args.seed)
              for j in range(v)]
    head = init_head_params(dim, args.seed) if links.is_last else None
    pipe = CrossSlicePipeline(stage_fn, links,
                              loss_head=loss_head if links.is_last
                              else None)
    losses: list[float] = []

    def feed():
        """This stage's input feed: inputs at stage 0, targets at the
        last stage — mid stages consume nothing (data=None below)."""
        step = 0
        while True:
            x, tgt = batch_for(step, m, mb, dim, args.seed)
            yield {"x": jnp.asarray(x)} if links.is_first \
                else {"tgt": jnp.asarray(tgt)}
            step += 1

    def step_fn(state, batch):
        params, head = state
        loss, grads, hgrads, _ = pipe.value_and_grad(
            params, num_microbatches=m,
            microbatches=batch["x"] if links.is_first else None,
            head_params=head,
            head_batches=batch["tgt"] if links.is_last else None)
        params = sgd(params, grads, args.lr)
        metrics = {}
        if links.is_last:
            head = sgd(head, hgrads, args.lr)
            losses.append(float(loss))
            metrics["loss"] = float(loss)
        return (params, head), metrics

    data = feed() if (links.is_first or links.is_last) else None
    try:
        (params, head), _ = run_training(
            step_fn, (params, head), data, args.steps,
            log_fn=lambda s, mtr, b: print(
                f"step {s} loss {mtr['loss']:.6f}" if "loss" in mtr
                else f"step {s}", flush=True),
            log_every=1)
    finally:
        links.close()
    if args.out:
        if v == 1:
            out = {f"p_{k}": np.asarray(a) for k, a in params.items()}
        else:
            # per-chunk params keyed by chunk index (chunk j = virtual
            # stage j*S + s)
            out = {f"p{j}_{k}": np.asarray(a)
                   for j, chunk in enumerate(params)
                   for k, a in chunk.items()}
        if links.is_last:
            out.update({f"h_{k}": np.asarray(a) for k, a in head.items()})
            out["losses"] = np.asarray(losses, np.float32)
        np.savez(f"{args.out}-stage{links.stage}.npz", **out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
