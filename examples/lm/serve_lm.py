"""Serve a trained LM checkpoint with continuous batching — the serving
half of examples/lm (train_lm.py trains, generate.py decodes one batch,
this serves a QUEUE of requests through a fixed pool of cache slots).

Demonstrates the serving feature matrix on a synthetic workload of
mixed-length requests:

- plain continuous batching (greedy or sampled via --temperature/--top_k/
  --top_p): finished requests release their cache slot to the next
  queued request mid-flight;
- speculative serving (--draft_preset): every slot runs
  draft-propose/target-verify rounds at its own frontier — token-exact
  greedy, or distribution-exact rejection sampling when a temperature is
  set.

Usage:
    python examples/lm/serve_lm.py --preset tiny --requests 12 --slots 4
    python examples/lm/serve_lm.py --preset small --draft_preset tiny \
        --requests 16 --slots 8 --temperature 0.8

The reference framework has no serving path (it delegates all compute —
SURVEY.md §2.3); this example exists so a user migrating from it can see
the green-field serving stack end to end.
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models import transformer as T
from tony_tpu.models.checkpoint import CheckpointManager
from tony_tpu.models.serve import (ContinuousBatcher,
                                   SpeculativeContinuousBatcher)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny", choices=sorted(T.PRESETS))
    parser.add_argument("--ckpt_dir", default="",
                        help="orbax checkpoint dir (empty = random params)")
    parser.add_argument("--draft_preset", default="",
                        help="enable speculative serving with this preset "
                             "as the draft (random params unless the "
                             "target checkpoint shape matches)")
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--prompt_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=32)
    parser.add_argument("--num_speculative", type=int, default=4)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--top_p", type=float, default=0.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kv_cache_dtype", default="model",
                        choices=("model", "int8"),
                        help="int8 = quantized KV cache (half the cache "
                             "HBM per slot; ~2x slots in the same memory)")
    parser.add_argument("--quantize_weights", action="store_true",
                        help="serve with weight-only int8 matmul weights "
                             "(half the weight HBM; see "
                             "models/quantize.py)")
    parser.add_argument("--attn_window", type=int, default=0,
                        help="sliding-window attention (0 = full causal)")
    parser.add_argument("--kv_cache_capacity", type=int, default=0,
                        help="rolling KV cache rows per slot (0 = "
                             "linear cache of max_len rows); requires "
                             "--attn_window, lifts the request-length "
                             "ceiling — O(capacity) memory however "
                             "long the stream")
    parser.add_argument("--no_pipeline", action="store_true",
                        help="sequential serve loop (the A/B baseline; "
                             "default is double-buffered dispatch — "
                             "chunk N+1 issued before chunk N's fetch)")
    parser.add_argument("--no_bucketed_admission", action="store_true",
                        help="per-length admission (compiles per "
                             "distinct prompt length; default pads to "
                             "power-of-two buckets and batches freed "
                             "slots into one dispatch)")
    args = parser.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    cfg = T.PRESETS[args.preset].scaled(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32, remat=False,
        kv_cache_dtype=args.kv_cache_dtype,
        attn_window=args.attn_window,
        kv_cache_capacity=args.kv_cache_capacity)
    params = T.init_params(jax.random.PRNGKey(0), cfg)
    if args.ckpt_dir:
        with CheckpointManager(args.ckpt_dir) as mgr:
            from tony_tpu.models.train import default_optimizer, init_state
            state = mgr.restore(
                template=init_state(params, default_optimizer()))
        params = state["params"]
        print(f"restored step {int(state['step'])} from {args.ckpt_dir}")
    if args.quantize_weights:
        from tony_tpu.models.quantize import quantize_weights_int8
        params = quantize_weights_int8(params)
        print("serving with weight-only int8 matmul weights")

    rs = np.random.RandomState(args.seed)
    # mixed lengths and budgets — the workload shape slot reuse exists for
    prompts = [list(rs.randint(0, cfg.vocab_size,
                               size=args.prompt_len))
               for _ in range(args.requests)]
    budgets = [int(b) for b in
               rs.randint(max(1, args.max_new_tokens // 4),
                          args.max_new_tokens + 1, size=args.requests)]
    max_len = args.prompt_len + args.max_new_tokens

    kw = dict(batch=args.slots, max_len=max_len,
              temperature=args.temperature, top_k=args.top_k,
              top_p=args.top_p, seed=args.seed,
              pipeline=not args.no_pipeline,
              bucketed_admission=not args.no_bucketed_admission)
    if args.draft_preset:
        # the draft must share the target's vocabulary (speculation
        # compares token ids), so override the preset's vocab_size
        draft_cfg = T.PRESETS[args.draft_preset].scaled(
            dtype=cfg.dtype, remat=False, vocab_size=cfg.vocab_size,
            kv_cache_dtype=args.kv_cache_dtype,
            attn_window=args.attn_window)
        draft_params = T.init_params(jax.random.PRNGKey(1), draft_cfg)
        if args.quantize_weights:
            from tony_tpu.models.quantize import quantize_weights_int8
            draft_params = quantize_weights_int8(draft_params)
        batcher = SpeculativeContinuousBatcher(
            params, cfg, draft_params, draft_cfg,
            num_speculative=args.num_speculative, **kw)
    else:
        batcher = ContinuousBatcher(params, cfg, **kw)

    t0 = time.perf_counter()
    outputs = batcher.serve(prompts, budgets)
    dt = time.perf_counter() - t0
    useful = sum(len(o) for o in outputs)
    mode = ("speculative " if args.draft_preset else "") + (
        "sampled" if args.temperature > 0 else "greedy")
    print(f"served {args.requests} requests ({useful} tokens) through "
          f"{args.slots} slots in {dt:.2f}s incl. compile — {mode}")
    if args.draft_preset:
        print(f"speculative rounds: {batcher.rounds_executed} "
              f"({useful / max(1, batcher.rounds_executed * args.slots):.2f}"
              f" tokens/slot-round)")
    else:
        print(f"decode steps: {batcher.steps_executed} "
              f"(slot-step utilization "
              f"{useful / max(1, batcher.steps_executed * args.slots):.2f})")
    phases = batcher.phase_times.summary()
    if phases:
        print("host phases:",
              "  ".join(f"{name} {v['total_s']:.2f}s/{v['count']}"
                        for name, v in phases.items()))
    print("first request tokens:", outputs[0][:12])
    return 0


if __name__ == "__main__":
    sys.exit(main())
