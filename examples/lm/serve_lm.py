"""Serve a trained LM checkpoint with continuous batching — the serving
half of examples/lm (train_lm.py trains, generate.py decodes one batch,
this serves a QUEUE of requests through a fixed pool of cache slots).

Demonstrates the serving feature matrix on a synthetic workload of
mixed-length requests:

- plain continuous batching (greedy or sampled via --temperature/--top_k/
  --top_p): finished requests release their cache slot to the next
  queued request mid-flight;
- speculative serving (--draft_preset): every slot runs
  draft-propose/target-verify rounds at its own frontier — token-exact
  greedy, or distribution-exact rejection sampling when a temperature is
  set.

Usage:
    python examples/lm/serve_lm.py --preset tiny --requests 12 --slots 4
    python examples/lm/serve_lm.py --preset small --draft_preset tiny \
        --requests 16 --slots 8 --temperature 0.8

Streaming data plane (tony_tpu/serving): the same batcher can serve a
live admission queue over the persistent TONYS1 token-push protocol —

    # a serving replica (model host)
    python examples/lm/serve_lm.py --preset tiny --slots 4 \
        --listen 0.0.0.0:7070
    # a router front-door spreading sessions across replicas (no model)
    python examples/lm/serve_lm.py --listen 0.0.0.0:7000 \
        --route host1:7070,host2:7070
    # a streaming client (no model): submits the synthetic workload and
    # prints client-side TTFT / inter-token latency
    python examples/lm/serve_lm.py --preset tiny --requests 12 \
        --connect host1:7000

Disaggregated prefill/decode (docs/serving.md): prefill gangs ship KV
packages to decode gangs over tensor channels, so admissions never
stall in-flight decode chunks —

    # one prefill host + one decode host (real multi-host shape)
    python examples/lm/serve_lm.py --preset tiny --role prefill \
        --listen 0.0.0.0:7071
    python examples/lm/serve_lm.py --preset tiny --slots 4 \
        --role decode --listen 0.0.0.0:7072
    # the router splits placement: ADMIT -> prefill tier,
    # TOKENS <- decode tier
    python examples/lm/serve_lm.py --listen 0.0.0.0:7000 \
        --route host1:7071 --route_decode host2:7072
    # or spawn all three locally and run the synthetic workload:
    python examples/lm/serve_lm.py --preset tiny --requests 12 \
        --slots 4 --disaggregate

Prefix-aware routing (docs/serving.md §Prefix-aware routing): register
a shared system prompt, compute its KV template ONCE, warm the other
replica in one template ship, and let the router place every session
where the prefix already lives —

    # replica B first, cold. Size --prompt_len to fit prefix+suffix:
    # a replica whose max_len leaves no room for the shipped prefix
    # rejects the template (request-scoped) and serves prefix-blind
    python examples/lm/serve_lm.py --preset tiny --slots 4 \
        --prompt_len 96 --listen 0.0.0.0:7071 &
    # replica A computes the prefix template and warms replica B in
    # ONE template ship (B runs zero prefix forwards)
    python examples/lm/serve_lm.py --preset tiny --slots 4 \
        --prompt_len 96 --listen 0.0.0.0:7070 \
        --shared_prefix_file sys_prompt.txt \
        --publish_prefix host2:7071
    # the router matches prompts against the registered prefix
    python examples/lm/serve_lm.py --listen 0.0.0.0:7000 \
        --route host1:7070,host2:7071 --shared_prefix_file sys_prompt.txt
    # prefix-heavy client traffic (every prompt continues the prefix)
    python examples/lm/serve_lm.py --preset tiny --requests 12 \
        --connect host1:7000 --shared_prefix_file sys_prompt.txt

The reference framework has no serving path (it delegates all compute —
SURVEY.md §2.3); this example exists so a user migrating from it can see
the green-field serving stack end to end.
"""

from __future__ import annotations

import argparse
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models import transformer as T
from tony_tpu.models.checkpoint import CheckpointManager
from tony_tpu.models.serve import (ContinuousBatcher,
                                   SpeculativeContinuousBatcher)


def _parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _load_prefix_tokens(path: str) -> list[int]:
    """Token ids from a whitespace/comma-separated file — the shared
    prefix (system prompt) the prefix-aware demo paths register,
    install, publish, and continue."""
    with open(path) as f:
        toks = [int(t) for t in f.read().replace(",", " ").split()]
    if not toks:
        raise SystemExit(f"{path}: no tokens")
    return toks


def _install_and_publish(args, server) -> None:
    """--shared_prefix_file on a serving host: make the prefix resident
    (ONE local prefill); --publish_prefix additionally warms a peer
    replica in one template ship over its prefix lane (the peer runs
    ZERO prefix forwards — docs/serving.md §Prefix-aware routing)."""
    toks = _load_prefix_tokens(args.shared_prefix_file)
    pid = server.install_prefix(toks)
    if pid is None:
        print("prefix NOT resident (rolling-cache layout); serving "
              "prefix-blind", flush=True)
        return
    print(f"prefix {pid} resident ({len(toks)} tokens)", flush=True)
    if args.publish_prefix:
        from tony_tpu.serving.client import StreamingClient

        host, port = _parse_addr(args.publish_prefix)
        with StreamingClient(host, port) as peer:
            lane = peer.hello.get("prefix_port")
        if lane is None:
            raise SystemExit(f"{args.publish_prefix} advertises no "
                             f"prefix lane")
        n = server.publish_prefix(pid, f"{host}:{lane}")
        print(f"published prefix {pid} to {host}:{lane} ({n} bytes — "
              f"the peer warmed without recomputing)", flush=True)


def _run_server(args, batcher) -> int:
    """--listen: drive the batcher's ServeEngine behind a streaming
    server until interrupted, then drain gracefully."""
    from tony_tpu.serving.server import ServingServer

    host, port = _parse_addr(args.listen)
    server = ServingServer(batcher, bind_host=host, port=port,
                           weights_version=args.weights_version or None)
    bound = server.start()
    if args.shared_prefix_file:
        _install_and_publish(args, server)
    mode = ("speculative " if args.draft_preset else "") + (
        "sampled" if args.temperature > 0 else "greedy")
    print(f"serving {args.preset} ({mode}) on {host}:{bound} with "
          f"{args.slots} slots — ^C drains and exits", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("draining in-flight requests ...", flush=True)
        server.stop(drain=True)
    return 0


def _run_router(args) -> int:
    """--listen + --route: the model-free front door. With
    --route_decode the router runs DISAGGREGATED placement — --route
    names the prefill tier, --route_decode the decode tier."""
    from tony_tpu.serving.router import ServingRouter

    host, port = _parse_addr(args.listen)
    replicas = [a.strip() for a in args.route.split(",") if a.strip()]
    decodes = [a.strip() for a in args.route_decode.split(",")
               if a.strip()]
    router = ServingRouter(replicas, bind_host=host, port=port,
                           decode_replicas=decodes or None)
    if args.shared_prefix_file:
        pid = router.register_prefix(
            _load_prefix_tokens(args.shared_prefix_file))
        print(f"prefix {pid} registered for tokenized matching",
              flush=True)
    bound = router.start()
    shape = (f"{len(replicas)} prefill + {len(decodes)} decode replicas"
             if decodes else f"{len(replicas)} replicas")
    print(f"routing on {host}:{bound} over {shape} — ^C exits",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.stop()
    return 0


def _run_prefill(args, params, cfg) -> int:
    """--role prefill --listen: the stateless prefill tier — no cache
    slots, no decode loop; prompts in, KV shipments out."""
    from tony_tpu.serving.disagg import PrefillServer

    host, port = _parse_addr(args.listen)
    shared = (_load_prefix_tokens(args.shared_prefix_file)
              if args.shared_prefix_file else [])
    server = PrefillServer(params, cfg,
                           max_len=(len(shared) + args.prompt_len
                                    + args.max_new_tokens),
                           seed=args.seed, max_batch=args.slots,
                           bind_host=host, port=port,
                           weights_version=args.weights_version or None)
    bound = server.start()
    if args.shared_prefix_file:
        _install_and_publish(args, server)
    print(f"prefill tier ({args.preset}) on {host}:{bound} "
          f"({args.slots}-row waves) — ^C exits", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def _run_decode(args, batcher) -> int:
    """--role decode --listen: the decode tier — admissions arrive as
    KV shipments on the channel hub, never as prompts."""
    from tony_tpu.serving.disagg import DecodeServer

    host, port = _parse_addr(args.listen)
    server = DecodeServer(batcher, bind_host=host, port=port,
                          weights_version=args.weights_version or None)
    bound = server.start()
    mode = "sampled" if args.temperature > 0 else "greedy"
    print(f"decode tier ({args.preset}, {mode}) on {host}:{bound} with "
          f"{args.slots} slots; kv channel on :{server.hub.port} — ^C "
          f"drains and exits", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("draining in-flight requests ...", flush=True)
        server.stop(drain=True)
    return 0


def _run_disaggregate(args, params, cfg, batcher, prompts,
                      budgets) -> int:
    """--disaggregate: spawn both tiers + the router in-process and
    stream the synthetic workload through the split — the one-command
    demo of the topology (--role is the real multi-host shape)."""
    import threading

    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.client import StreamingClient
    from tony_tpu.serving.disagg import DecodeServer, PrefillServer
    from tony_tpu.serving.router import ServingRouter

    shared = (_load_prefix_tokens(args.shared_prefix_file)
              if args.shared_prefix_file else [])
    max_len = len(shared) + args.prompt_len + args.max_new_tokens
    reg = M.get_default()
    pre = PrefillServer(params, cfg, max_len=max_len, seed=args.seed,
                        max_batch=args.slots)
    dec = DecodeServer(batcher)
    router = ServingRouter([f"127.0.0.1:{pre.start()}"],
                           decode_replicas=[f"127.0.0.1:{dec.start()}"])
    if shared:
        pid = pre.install_prefix(shared)
        if pid is not None:
            router.register_prefix(shared, prefix_id=pid)
            print(f"prefix {pid} resident at the prefill tier "
                  f"({len(shared)} tokens); suffix-only prefill waves",
                  flush=True)
    rport = router.start()
    print(f"disaggregated: prefill :{pre.port} -> decode :{dec.port} "
          f"(kv channel :{dec.hub.port}), router :{rport}", flush=True)
    outs: list = [None] * args.requests
    ttfts: list = [0.0] * args.requests
    gaps: list[float] = []
    try:
        with StreamingClient("127.0.0.1", rport) as client:
            def drain(i, rid, t_submit):
                toks, last = [], None
                for delta in client.deltas(rid):
                    now = time.perf_counter()
                    if last is None:
                        ttfts[i] = now - t_submit
                    else:
                        gaps.append((now - last) / len(delta))
                    last = now
                    toks.extend(delta)
                outs[i] = toks

            t0 = time.perf_counter()
            threads = []
            for i, p in enumerate(prompts):
                rid = client.submit(p, budgets[i])
                th = threading.Thread(target=drain,
                                      args=(i, rid, time.perf_counter()))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            dt = time.perf_counter() - t0
    finally:
        router.stop()
        pre.stop()
        dec.stop()
    useful = sum(len(o) for o in outs if o)
    ship = reg.histogram("tony_kv_ship_seconds")
    print(f"streamed {args.requests} requests ({useful} tokens) in "
          f"{dt:.2f}s — {useful / max(dt, 1e-9):.1f} tok/s")
    ttfts_s = sorted(ttfts)
    print(f"ttft: p50 {ttfts_s[len(ttfts_s) // 2] * 1e3:.0f} ms  "
          f"max {ttfts_s[-1] * 1e3:.0f} ms;  inter-token mean "
          f"{(sum(gaps) / len(gaps) * 1e3) if gaps else 0.0:.1f} ms")
    if ship.count:
        print(f"kv handoff: {ship.count} shipments, mean wall "
              f"{ship.sum / ship.count * 1e3:.1f} ms")
    print("first request tokens:", (outs[0] or [])[:12])
    return 0


def _run_client(args) -> int:
    """--connect: submit the synthetic workload over one persistent
    streaming connection and report client-side TTFT / inter-token
    latency. No model is built — prompt tokens draw from the named
    preset's vocab, which must match the server's."""
    import threading

    from tony_tpu.models import transformer as T
    from tony_tpu.serving.client import ServerBusy, StreamingClient

    host, port = _parse_addr(args.connect)
    if args.drain:
        # operator mode: ask the ROUTER to live-migrate every session
        # off a replica, print the summary, exit (docs/serving.md
        # §Operating the fleet)
        with StreamingClient(host, port) as client:
            res = client.drain_replica(args.drain)
        print(f"drain {args.drain}: {res}")
        return 0 if res.get("ok") else 1
    vocab = T.PRESETS[args.preset].vocab_size
    rs = np.random.RandomState(args.seed)
    # with a shared prefix the workload is PREFIX-HEAVY: every prompt
    # continues the same system prompt (the router's tokenized match
    # finds it — no prefix id is sent; the prefix-aware fleet places
    # each session where the prefix KV already lives)
    shared = (_load_prefix_tokens(args.shared_prefix_file)
              if args.shared_prefix_file else [])
    prompts = [shared + [int(t) for t in rs.randint(0, vocab,
                                                    size=args.prompt_len)]
               for _ in range(args.requests)]
    budgets = [int(b) for b in
               rs.randint(max(1, args.max_new_tokens // 4),
                          args.max_new_tokens + 1, size=args.requests)]
    # QoS classes: one class for every request (--request_class), or
    # the mixed-class mode — a deterministic interactive/standard/batch
    # rotation that exercises replica-side priority, preemption, and
    # shedding, reported per class
    classes = None
    if args.mixed_classes:
        cyc = ("interactive", "standard", "batch")
        classes = [cyc[i % len(cyc)] for i in range(args.requests)]
    elif args.request_class:
        classes = [args.request_class] * args.requests
    outs: list = [None] * args.requests
    ttfts: list = [0.0] * args.requests
    gaps: list = [[] for _ in range(args.requests)]
    shed: list = [False] * args.requests

    with StreamingClient(host, port) as client:
        print(f"connected to {host}:{port}: {client.hello}")

        def drain(i, rid, t_submit):
            toks, last = [], None
            try:
                for delta in client.deltas(rid):
                    now = time.perf_counter()
                    if last is None:
                        ttfts[i] = now - t_submit
                    else:
                        gaps[i].append((now - last) / len(delta))
                    last = now
                    toks.extend(delta)
            except ServerBusy as e:
                # the fleet shed this request even after the client's
                # retry budget — overload said no, and that IS the
                # answer (report it, don't crash the workload)
                shed[i] = True
                print(f"request {i} shed (retry after "
                      f"{e.retry_after_ms}ms)", flush=True)
                return
            outs[i] = toks

        t0 = time.perf_counter()
        threads = []
        for i, p in enumerate(prompts):
            rid = client.submit(
                p, budgets[i],
                request_class=classes[i] if classes else None,
                retries=args.busy_retries)
            th = threading.Thread(target=drain,
                                  args=(i, rid, time.perf_counter()))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0

    useful = sum(len(o) for o in outs if o)
    print(f"streamed {args.requests} requests ({useful} tokens) in "
          f"{dt:.2f}s — {useful / max(dt, 1e-9):.1f} tok/s")

    def _report(label, idx):
        tt = sorted(ttfts[i] for i in idx if outs[i] is not None)
        gp = [g for i in idx for g in gaps[i]]
        n_shed = sum(1 for i in idx if shed[i])
        if not tt:
            print(f"{label}: no completed requests"
                  + (f" ({n_shed} shed)" if n_shed else ""))
            return
        line = (f"{label}: ttft p50 {tt[len(tt) // 2] * 1e3:.0f} ms  "
                f"max {tt[-1] * 1e3:.0f} ms;  inter-token mean "
                f"{(sum(gp) / len(gp) * 1e3) if gp else 0.0:.1f} ms")
        if n_shed:
            line += f"  ({n_shed} shed)"
        print(line)

    _report("ttft", range(args.requests))
    if classes:
        for c in ("interactive", "standard", "batch"):
            idx = [i for i in range(args.requests) if classes[i] == c]
            if idx:
                _report(f"  {c} ({len(idx)} reqs)", idx)
    first = next((o for o in outs if o), [])
    print("first request tokens:", first[:12])
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="tiny", choices=sorted(T.PRESETS))
    parser.add_argument("--ckpt_dir", default="",
                        help="orbax checkpoint dir (empty = random params)")
    parser.add_argument("--draft_preset", default="",
                        help="enable speculative serving with this preset "
                             "as the draft (random params unless the "
                             "target checkpoint shape matches)")
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--prompt_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=32)
    parser.add_argument("--num_speculative", type=int, default=4)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--top_p", type=float, default=0.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--kv_cache_dtype", default="model",
                        choices=("model", "int8"),
                        help="int8 = quantized KV cache (half the cache "
                             "HBM per slot; ~2x slots in the same memory)")
    parser.add_argument("--quantize_weights", action="store_true",
                        help="serve with weight-only int8 matmul weights "
                             "(half the weight HBM; see "
                             "models/quantize.py)")
    parser.add_argument("--attn_window", type=int, default=0,
                        help="sliding-window attention (0 = full causal)")
    parser.add_argument("--kv_cache_capacity", type=int, default=0,
                        help="rolling KV cache rows per slot (0 = "
                             "linear cache of max_len rows); requires "
                             "--attn_window, lifts the request-length "
                             "ceiling — O(capacity) memory however "
                             "long the stream")
    parser.add_argument("--no_pipeline", action="store_true",
                        help="sequential serve loop (the A/B baseline; "
                             "default is double-buffered dispatch — "
                             "chunk N+1 issued before chunk N's fetch)")
    parser.add_argument("--no_bucketed_admission", action="store_true",
                        help="per-length admission (compiles per "
                             "distinct prompt length; default pads to "
                             "power-of-two buckets and batches freed "
                             "slots into one dispatch)")
    parser.add_argument("--warm_from", default="", metavar="HOST:PORT",
                        help="warm boot: pull content-addressed "
                             "weights peer-to-peer from a serving "
                             "replica's weights lane instead of a "
                             "storage load (falls back to "
                             "--ckpt_dir / random params on failure)")
    parser.add_argument("--listen", default="", metavar="HOST:PORT",
                        help="serve a LIVE admission queue over the "
                             "TONYS1 streaming protocol instead of the "
                             "fixed synthetic workload (with --route: "
                             "run the router front-door instead)")
    parser.add_argument("--connect", default="", metavar="HOST:PORT",
                        help="run as a streaming CLIENT against a "
                             "--listen server or router (no local "
                             "model; prints TTFT/ITL)")
    parser.add_argument("--route", default="",
                        metavar="HOST:PORT,HOST:PORT",
                        help="with --listen: route sessions across "
                             "these replica servers by queue depth "
                             "(no local model)")
    parser.add_argument("--route_decode", default="",
                        metavar="HOST:PORT,HOST:PORT",
                        help="with --route: DISAGGREGATED placement — "
                             "--route names the prefill tier, this the "
                             "decode tier (ADMIT to prefill, TOKENS "
                             "from decode)")
    parser.add_argument("--role", default="", choices=("", "prefill",
                                                       "decode"),
                        help="with --listen: run ONE tier of "
                             "disaggregated serving on this host "
                             "instead of a colocated replica")
    parser.add_argument("--disaggregate", action="store_true",
                        help="spawn prefill + decode + router locally "
                             "and stream the synthetic workload "
                             "through the split (the one-command demo; "
                             "--role is the real multi-host shape)")
    parser.add_argument("--shared_prefix_file", default="",
                        metavar="PATH",
                        help="token-id file of a shared prefix (system "
                             "prompt). Server/prefill: install its KV "
                             "template (prefix-hit admissions run only "
                             "their suffix); router: register it for "
                             "tokenized matching; client: prepend it "
                             "to every synthetic prompt (prefix-heavy "
                             "traffic)")
    parser.add_argument("--weights_version", default="",
                        help="with --listen: the weights generation "
                             "this replica advertises (HELLO/STATS). "
                             "Routers pin each session to its first "
                             "placement's version, which is what makes "
                             "drain-by-drain rolling upgrades "
                             "session-transparent (docs/serving.md "
                             "§Operating the fleet)")
    parser.add_argument("--request_class", default="",
                        choices=("", "interactive", "standard", "batch"),
                        help="with --connect: submit every request at "
                             "this QoS tier (empty = classless wire — "
                             "servers default it to standard)")
    parser.add_argument("--mixed_classes", action="store_true",
                        help="with --connect: rotate requests through "
                             "interactive/standard/batch and report "
                             "TTFT/ITL per class (the QoS demo "
                             "workload)")
    parser.add_argument("--busy_retries", type=int, default=0,
                        help="with --connect: transparent re-admissions "
                             "per request when the fleet sheds it with "
                             "BUSY (capped jittered backoff on the "
                             "server's hint)")
    parser.add_argument("--drain", default="", metavar="HOST:PORT",
                        help="with --connect to a ROUTER: fence this "
                             "replica and live-migrate every session "
                             "off it (planned maintenance), print the "
                             "summary, exit")
    parser.add_argument("--publish_prefix", default="",
                        metavar="HOST:PORT",
                        help="with --listen + --shared_prefix_file: "
                             "after installing, warm the peer replica "
                             "at this serving address in ONE template "
                             "ship over its prefix lane (the peer "
                             "recomputes nothing)")
    args = parser.parse_args()
    if args.publish_prefix and not (args.shared_prefix_file
                                    and args.listen):
        parser.error("--publish_prefix requires --listen and "
                     "--shared_prefix_file")
    if args.drain and not args.connect:
        parser.error("--drain requires --connect (a router address)")
    if (args.mixed_classes or args.request_class) and not args.connect:
        parser.error("--request_class/--mixed_classes require "
                     "--connect (they shape CLIENT traffic)")
    if args.mixed_classes and args.request_class:
        parser.error("--mixed_classes and --request_class are "
                     "mutually exclusive")

    if args.connect:
        return _run_client(args)
    if args.route or args.route_decode:
        if not args.listen:
            parser.error("--route requires --listen")
        if args.route_decode and not args.route:
            parser.error("--route_decode requires --route")
        return _run_router(args)
    if (args.role or args.disaggregate) and args.draft_preset:
        parser.error("speculative serving is not supported "
                     "disaggregated (the KV shipment carries no "
                     "draft-model cache)")
    if args.role and not args.listen:
        parser.error("--role requires --listen")

    on_tpu = jax.default_backend() == "tpu"
    cfg = T.PRESETS[args.preset].scaled(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32, remat=False,
        kv_cache_dtype=args.kv_cache_dtype,
        attn_window=args.attn_window,
        kv_cache_capacity=args.kv_cache_capacity)
    params = None
    if args.warm_from:
        from tony_tpu.serving.weightstore import pull_weights
        try:
            meta, params = pull_weights(args.warm_from)
            print(f"warm boot: pulled weights "
                  f"{meta['digest'][:12]}… from {args.warm_from}")
        except Exception as e:              # noqa: BLE001 — degrade
            print(f"warm boot from {args.warm_from} failed ({e}); "
                  f"falling back to a storage load")
    if params is None:
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        if args.ckpt_dir:
            with CheckpointManager(args.ckpt_dir) as mgr:
                from tony_tpu.models.train import (default_optimizer,
                                                   init_state)
                state = mgr.restore(
                    template=init_state(params, default_optimizer()))
            params = state["params"]
            print(f"restored step {int(state['step'])} from "
                  f"{args.ckpt_dir}")
    if args.quantize_weights:
        from tony_tpu.models.quantize import quantize_weights_int8
        params = quantize_weights_int8(params)
        print("serving with weight-only int8 matmul weights")

    if args.role == "prefill":
        return _run_prefill(args, params, cfg)

    rs = np.random.RandomState(args.seed)
    shared = (_load_prefix_tokens(args.shared_prefix_file)
              if args.shared_prefix_file else [])
    # mixed lengths and budgets — the workload shape slot reuse exists
    # for; with a shared prefix every prompt continues it
    prompts = [shared + list(rs.randint(0, cfg.vocab_size,
                                        size=args.prompt_len))
               for _ in range(args.requests)]
    budgets = [int(b) for b in
               rs.randint(max(1, args.max_new_tokens // 4),
                          args.max_new_tokens + 1, size=args.requests)]
    max_len = len(shared) + args.prompt_len + args.max_new_tokens

    kw = dict(batch=args.slots, max_len=max_len,
              temperature=args.temperature, top_k=args.top_k,
              top_p=args.top_p, seed=args.seed,
              pipeline=not args.no_pipeline,
              bucketed_admission=not args.no_bucketed_admission)
    if args.draft_preset:
        # the draft must share the target's vocabulary (speculation
        # compares token ids), so override the preset's vocab_size
        draft_cfg = T.PRESETS[args.draft_preset].scaled(
            dtype=cfg.dtype, remat=False, vocab_size=cfg.vocab_size,
            kv_cache_dtype=args.kv_cache_dtype,
            attn_window=args.attn_window)
        draft_params = T.init_params(jax.random.PRNGKey(1), draft_cfg)
        if args.quantize_weights:
            from tony_tpu.models.quantize import quantize_weights_int8
            draft_params = quantize_weights_int8(draft_params)
        batcher = SpeculativeContinuousBatcher(
            params, cfg, draft_params, draft_cfg,
            num_speculative=args.num_speculative, **kw)
    else:
        batcher = ContinuousBatcher(params, cfg, **kw)

    if args.role == "decode":
        return _run_decode(args, batcher)
    if args.disaggregate:
        return _run_disaggregate(args, params, cfg, batcher, prompts,
                                 budgets)
    if args.listen:
        return _run_server(args, batcher)

    if shared:
        # the local demo of the admission fast path: resident template,
        # suffix-only admissions, token-identical output
        from tony_tpu.serving.prefix import fingerprint
        if batcher.install_prefix(fingerprint(shared), shared):
            print(f"prefix resident locally ({len(shared)} tokens); "
                  f"prefix-hit admissions run suffix-only")

    t0 = time.perf_counter()
    outputs = batcher.serve(prompts, budgets)
    dt = time.perf_counter() - t0
    useful = sum(len(o) for o in outputs)
    mode = ("speculative " if args.draft_preset else "") + (
        "sampled" if args.temperature > 0 else "greedy")
    print(f"served {args.requests} requests ({useful} tokens) through "
          f"{args.slots} slots in {dt:.2f}s incl. compile — {mode}")
    if args.draft_preset:
        print(f"speculative rounds: {batcher.rounds_executed} "
              f"({useful / max(1, batcher.rounds_executed * args.slots):.2f}"
              f" tokens/slot-round)")
    else:
        print(f"decode steps: {batcher.steps_executed} "
              f"(slot-step utilization "
              f"{useful / max(1, batcher.steps_executed * args.slots):.2f})")
    phases = batcher.phase_times.summary()
    if phases:
        print("host phases:",
              "  ".join(f"{name} {v['total_s']:.2f}s/{v['count']}"
                        for name, v in phases.items()))
    print("first request tokens:", outputs[0][:12])
    return 0


if __name__ == "__main__":
    sys.exit(main())
