"""Distributed MNIST in TensorFlow 2 under the tony-tpu orchestrator.

Reference-parity example (reference: tony-examples/mnist-tensorflow/
mnist_distributed.py — TF1 PS/worker with tf.train.Server and
MonitoredTrainingSession). Modernized to TF2: the framework's TensorFlow
runtime adapter exports ``TF_CONFIG`` (tony_tpu/cluster/executor.py
framework_env, the reference's Utils.constructTFConfig:383 analog) and
``MultiWorkerMirroredStrategy`` consumes it directly — no PS job type
needed, sync all-reduce DP like the reference's PyTorch recipe.

Requires the ``tensorflow`` package (NOT bundled with tony-tpu — this
example runs wherever the user's venv provides TF, e.g. via
--python_venv). The JAX example (examples/mnist/) is the TPU-native path.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.application.framework=tensorflow \
        --conf tony.worker.instances=2 \
        --src_dir examples \
        --executes 'python examples/mnist-tensorflow/mnist_distributed.py'
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

try:
    import tensorflow as tf
except ImportError:  # pragma: no cover - env without TF
    print("this example requires tensorflow (ship it via --python_venv)",
          file=sys.stderr)
    sys.exit(2)


def synthetic_mnist(n: int, seed: int):
    rng = np.random.RandomState(seed)
    templates = np.random.RandomState(0).rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=(n,)).astype(np.int64)
    images = templates[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return images.reshape(n, -1), labels


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=64,
                        help="per-worker batch size")
    args = parser.parse_args()

    tf_config = json.loads(os.environ.get("TF_CONFIG", "{}"))
    task = tf_config.get("task", {})
    print(f"TF_CONFIG task: {task}", flush=True)

    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    num_workers = strategy.num_replicas_in_sync
    print(f"{num_workers} replicas in sync", flush=True)

    # Custom training loop (the Keras-3 bundled with TF no longer supports
    # model.fit under TF distribution strategies): variables created in
    # strategy scope, per-step gradients all-reduced by strategy.run — the
    # TF2 equivalent of the reference's PS/MonitoredTrainingSession loop.
    with strategy.scope():
        w1 = tf.Variable(tf.random.normal([784, 128], stddev=0.05, seed=0))
        b1 = tf.Variable(tf.zeros([128]))
        w2 = tf.Variable(tf.random.normal([128, 10], stddev=0.05, seed=1))
        b2 = tf.Variable(tf.zeros([10]))
        optimizer = tf.keras.optimizers.SGD(0.1)

    def replica_step(x, y):
        with tf.GradientTape() as tape:
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            logits = tf.matmul(h, w2) + b2
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=y, logits=logits))
        grads = tape.gradient(loss, [w1, b1, w2, b2])
        optimizer.apply_gradients(zip(grads, [w1, b1, w2, b2]))
        return loss

    @tf.function
    def train_step(x, y):
        per_replica = strategy.run(replica_step, args=(x, y))
        return strategy.reduce(tf.distribute.ReduceOp.MEAN, per_replica,
                               axis=None)

    x, y = synthetic_mnist(512 * args.batch_size,
                           seed=int(task.get("index", 0)))
    final_loss = float("nan")
    for step in range(args.steps):
        i = (step * args.batch_size) % (len(x) - args.batch_size)
        bx = tf.constant(x[i:i + args.batch_size])
        by = tf.constant(y[i:i + args.batch_size])
        final_loss = float(train_step(bx, by))
        if step % 20 == 0:
            print(f"step {step} loss {final_loss:.4f}", flush=True)
    print(f"final loss {final_loss:.4f}", flush=True)
    return 0 if np.isfinite(final_loss) else 1


if __name__ == "__main__":
    sys.exit(main())
