"""Distributed MNIST in PyTorch under the tony-tpu orchestrator.

Reference-parity example (reference: tony-examples/mnist-pytorch/
mnist_distributed.py:113-226): the framework's PyTorch runtime adapter
exports ``RANK`` / ``WORLD`` / ``INIT_METHOD`` (tcp:// rendezvous at
worker 0 — tony_tpu/cluster/executor.py framework_env), the script builds a
``torch.distributed`` gloo process group from them and all-reduces gradients
by hand, exactly the reference's recipe. This is the CPU/GPU escape hatch —
the JAX example (examples/mnist/) is the TPU-native path.

Usage:
    python -m tony_tpu.client.cli submit \
        --conf tony.application.framework=pytorch \
        --conf tony.worker.instances=2 \
        --src_dir examples \
        --executes 'python examples/mnist-pytorch/mnist_distributed.py'
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import torch
import torch.distributed as dist
import torch.nn as nn
import torch.nn.functional as F


def synthetic_mnist(n: int, seed: int):
    rng = np.random.RandomState(seed)
    templates = np.random.RandomState(0).rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, size=(n,)).astype(np.int64)
    images = templates[labels] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return torch.from_numpy(images.reshape(n, -1)), torch.from_numpy(labels)


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def average_gradients(model: nn.Module, world: int) -> None:
    """Manual sync-DP all-reduce (reference: mnist_distributed.py:113-126)."""
    for p in model.parameters():
        if p.grad is not None:
            dist.all_reduce(p.grad.data, op=dist.ReduceOp.SUM)
            p.grad.data /= world


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()

    # The executor-exported rendezvous (reference: TaskExecutor.java:142-153).
    rank = int(os.environ.get("RANK", "0"))
    world = int(os.environ.get("WORLD", "1"))
    init_method = os.environ.get("INIT_METHOD", "")
    if world > 1:
        dist.init_process_group("gloo", init_method=init_method,
                                rank=rank, world_size=world)
        print(f"[rank {rank}/{world}] process group up via {init_method}",
              flush=True)

    torch.manual_seed(rank)
    images, labels = synthetic_mnist(512 * args.batch_size, seed=rank)
    model = Net()
    if world > 1:   # identical init everywhere: broadcast rank 0's weights
        for p in model.parameters():
            dist.broadcast(p.data, src=0)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr)

    final_loss = None
    for step in range(args.steps):
        i = (step * args.batch_size) % (len(images) - args.batch_size)
        x, y = images[i:i + args.batch_size], labels[i:i + args.batch_size]
        opt.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        if world > 1:
            average_gradients(model, world)
        opt.step()
        final_loss = loss.item()
        if rank == 0 and step % 20 == 0:
            print(f"step {step} loss {final_loss:.4f}", flush=True)

    if world > 1:
        dist.barrier()
        dist.destroy_process_group()
    if rank == 0:
        print(f"final loss {final_loss:.4f}", flush=True)
    return 0 if final_loss is not None and final_loss < 2.5 else 1


if __name__ == "__main__":
    sys.exit(main())
