"""Benchmark: flagship transformer train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no performance numbers (BASELINE.md: "published":
{}), so ``vs_baseline`` is measured in-run against the naive formulation of
the same model — dense O(S²) attention and no fused kernels — i.e. what a
line-for-line port of a CUDA/torch-style model to jax would do. Values > 1
mean the framework's TPU-first path (flash-attention pallas kernels, bf16
MXU matmuls, fused norms) beats the naive port on the same hardware.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def _bench_step(step, state, batch, iters: int) -> float:
    state, m = step(state, batch)            # compile + warm
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    # Host fetch, not block_until_ready: on tunneled/remote platforms
    # block_until_ready can return before execution finishes, faking
    # microsecond steps; a device->host value read cannot.
    float(m["loss"])
    return (time.perf_counter() - t0) / iters


def main() -> None:
    from tony_tpu.models import transformer as T
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # 512d/8L bf16, seq 1024. remat off (this size fits HBM comfortably
        # on one chip, ~7% faster) and layers fully unrolled (drops the
        # scan's activation-stacking DUS ops, ~6% faster; compile cost is
        # paid once).
        cfg = T.PRESETS["small"].scaled(remat=False, scan_unroll=8)
        batch, seq, iters = 8, 1024, 20
    else:                                    # CPU smoke fallback
        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32)
        batch, seq, iters = 2, 128, 3

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)
    data = {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}

    def run(config) -> float:
        params = T.init_params(jax.random.PRNGKey(0), config)
        opt = default_optimizer(lr=1e-3)
        state = init_state(params, opt)
        step = make_train_step(
            lambda p, b: T.lm_loss(p, b, config), opt)
        return _bench_step(step, state, data, iters)

    t_framework = run(cfg)

    # Naive port baseline: f32 params/compute, dense attention (remat off so
    # it is the straight autodiff graph a naive port gets).
    import tony_tpu.models.transformer as tmod
    naive_cfg = cfg.scaled(dtype=jnp.float32, remat=False)
    orig = tmod._attention
    tmod._attention = lambda q, k, v, *a: tmod.reference_attention(
        q, k, v, causal=True)
    try:
        t_naive = run(naive_cfg)
    finally:
        tmod._attention = orig

    tokens_per_sec = batch * seq / t_framework
    print(json.dumps({
        "metric": "flagship_lm_train_throughput",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(t_naive / t_framework, 3),
    }))


if __name__ == "__main__":
    main()
