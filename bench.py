"""Benchmark: flagship transformer train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no performance numbers (BASELINE.md: "published":
{}), so ``vs_baseline`` is measured in-run against the naive formulation of
the same model — dense O(S²) attention and no fused kernels — i.e. what a
line-for-line port of a CUDA/torch-style model to jax would do. Values > 1
mean the framework's TPU-first path (flash-attention pallas kernels, bf16
MXU matmuls, fused norms) beats the naive port on the same hardware.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp

# Peak dense bf16 FLOP/s per chip, keyed by substring of device_kind.
# Order matters: more specific names first ("v5 lite" before "v5").
_PEAK_FLOPS = (
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def _peak_flops() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    for name, peak in _PEAK_FLOPS:
        if name in kind:
            return peak
    return None


def _bench_step(step, state, batch, iters: int, reps: int = 3) -> float:
    """Median-of-windows step time. The shared/tunneled chip's effective
    speed drifts ±15% across seconds (docs/performance.md measurement
    hygiene); a single window can record a bad minute as the framework's
    throughput, so each config is timed over ``reps`` windows and the
    median wins. Host value fetch, not block_until_ready: on tunneled
    platforms the latter can return before execution finishes, faking
    microsecond steps."""
    state, m = step(state, batch)            # compile + warm
    float(m["loss"])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, batch)
        float(m["loss"])
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


def main() -> None:
    import os
    # ~2/3 of a cold bench run is XLA compilation (6 jitted programs); the
    # persistent cache makes repeat runs start measuring immediately.
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    from tony_tpu.models import transformer as T
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # 512d/8L bf16, seq 1024. remat off (this size fits HBM comfortably
        # on one chip, ~7% faster), layers fully unrolled (drops the
        # scan's activation-stacking DUS ops, ~6% faster; compile cost is
        # paid once), batch 32 (+12% over 16 in interleaved A/B once bf16
        # logits storage freed the headroom).
        cfg = T.PRESETS["small"].scaled(remat=False, scan_unroll=8)
        batch, seq, iters = 32, 1024, 20
    else:                                    # CPU smoke fallback
        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32)
        batch, seq, iters = 2, 128, 3

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)
    data = {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}

    def run(config, run_data, run_iters, reps=3) -> float:
        params = T.init_params(jax.random.PRNGKey(0), config)
        opt = default_optimizer(lr=1e-3)
        state = init_state(params, opt)
        step = make_train_step(
            lambda p, b: T.lm_loss(p, b, config), opt)
        return _bench_step(step, state, run_data, run_iters, reps=reps)

    t_framework = run(cfg, data, iters)

    # Naive port baseline: f32 params/compute, dense attention (remat off so
    # it is the straight autodiff graph a naive port gets). Run at batch 8 —
    # the naive formulation's own best config: at batch 16 its f32 dense
    # attention residuals blow past HBM and it collapses pathologically,
    # which would flatter vs_baseline. Compare per-token throughput.
    import tony_tpu.models.transformer as tmod
    naive_cfg = cfg.scaled(dtype=jnp.float32, remat=False)
    n_batch = min(batch, 8)
    n_data = {k: v[:n_batch] for k, v in data.items()}
    orig = tmod._attention
    tmod._attention = lambda q, k, v, *a: tmod.reference_attention(
        q, k, v, causal=True)
    try:
        # 2 windows: the RATIO tolerates drift better than absolute numbers
        t_naive = run(naive_cfg, n_data, iters, reps=2)
    finally:
        tmod._attention = orig

    tokens_per_sec = batch * seq / t_framework
    naive_tokens_per_sec = n_batch * seq / t_naive
    out = {
        "metric": "flagship_lm_train_throughput",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / naive_tokens_per_sec, 3),
    }

    peak = _peak_flops()
    if peak is not None:
        flops_tok = T.train_flops_per_token(cfg, seq)
        out["mfu"] = round(tokens_per_sec * flops_tok / peak, 4)
        out["device"] = jax.devices()[0].device_kind

    if on_tpu:
        # Secondary: KV-cache autoregressive decode throughput (the serving
        # path: prefill + scan-decode as one compiled program).
        from tony_tpu.models.decode import generate
        d_batch, d_prompt, d_new = 16, 128, 256
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3),
                                    (d_batch, d_prompt), 0, cfg.vocab_size)
        # generate is already jit-compiled (static cfg/lengths)
        gen = functools.partial(generate, cfg=cfg, max_new_tokens=d_new,
                                temperature=0.0)
        dec = gen(params, prompt, rng=jax.random.PRNGKey(4))
        int(dec.tokens[0, 0])                    # compile + warm
        t0 = time.perf_counter()
        for i in range(3):
            dec = gen(params, prompt, rng=jax.random.PRNGKey(5 + i))
        int(dec.tokens[0, 0])
        t_dec = (time.perf_counter() - t0) / 3
        decode_tps = round(d_batch * d_new / t_dec, 1)
        # GQA decode (n_kv_heads=2): the grouped cache read + GQA-native
        # prefill kernels cut the decode-roofline HBM traffic — recorded
        # as its own arm since the model differs from the MHA flagship.
        gqa_cfg = cfg.scaled(n_kv_heads=2)
        gqa_params = T.init_params(jax.random.PRNGKey(0), gqa_cfg)
        gqa_gen = functools.partial(generate, cfg=gqa_cfg,
                                    max_new_tokens=d_new, temperature=0.0)
        dec = gqa_gen(gqa_params, prompt, rng=jax.random.PRNGKey(4))
        int(dec.tokens[0, 0])                    # compile + warm
        t0 = time.perf_counter()
        for i in range(3):
            dec = gqa_gen(gqa_params, prompt, rng=jax.random.PRNGKey(9 + i))
        int(dec.tokens[0, 0])
        decode_gqa_tps = round(d_batch * d_new * 3
                               / (time.perf_counter() - t0), 1)
        out["decode_gqa_tokens_per_s"] = decode_gqa_tps
        del gqa_params, gqa_gen
        del params, prompt, dec, gen   # free HBM before the tight base run

        def secondary(name, config, s_batch, s_seq, s_iters, key,
                      with_mfu=True):
            toks = jax.random.randint(jax.random.PRNGKey(key),
                                      (s_batch, s_seq + 1), 0,
                                      config.vocab_size)
            s_data = {"inputs": toks[:, :s_seq], "targets": toks[:, 1:]}
            tps = s_batch * s_seq / run(config, s_data, s_iters, reps=2)
            out[f"{name}_tokens_per_s"] = round(tps, 1)
            if with_mfu and peak is not None:
                out[f"{name}_mfu"] = round(
                    tps * T.train_flops_per_token(config, s_seq) / peak, 4)

        # GQA flagship (n_kv_heads=2): the grouped-query training win the
        # GQA-native kernels buy (K/V projections + attention K/V reads
        # ÷4). MFU accounting is GQA-aware (train_flops_per_token).
        secondary("gqa", cfg.scaled(n_kv_heads=2), batch, seq, 15, key=8)
        # "base" preset (768d/12L, BERT-base scale) at seq 2048 — stresses
        # framework overheads the small preset doesn't. remat off fits at
        # batch 8 on 16G HBM and is ~25% faster than remat at b=4.
        secondary("base", T.PRESETS["base"].scaled(remat=False,
                                                   scan_unroll=12),
                  8, 2048, 10, key=2)
        out["decode_tokens_per_s"] = decode_tps
        # "large" preset (1536d/24L, 1.0B params) — remat on (the optimizer
        # state already takes ~8 GB of HBM); the bigger matmuls give the
        # best MFU of any preset.
        secondary("large", T.PRESETS["large"], 4, 1024, 8, key=7)
        # long context (seq 8192) — the regime where attention dominates
        # layer FLOPs. Batch 4 is ~4% over 2 (interleaved A/B) and fits.
        # MFU recorded so the fused-vs-two-pass backward budget decision
        # (ops/attention.py _FUSED_PARTIALS_BYTES) has an efficiency
        # number to regress against.
        secondary("seq8k", cfg, 4, 8192, 10, key=6)
        # sliding-window attention at the same shape: the kernels triage
        # out-of-window blocks like above-diagonal ones (skip + DMA
        # elision), so attention cost goes O(seq·window). Measured 1.34x
        # over full causal at this shape when introduced (round 5).
        secondary("seq8k_win1k", cfg.scaled(attn_window=1024), 4, 8192,
                  10, key=6)
        # extreme context (seq 32768, b1) under the attention-output-save
        # remat policy (round 5): saving the flash o/lse lets the
        # backward skip re-running the O(S²) forward kernel — +19%
        # measured over remat="full" at this shape.
        secondary("seq32k", T.PRESETS["small"].scaled(
            remat=True, remat_policy="attn"), 1, 32768, 5, key=9)
        # sliding window at extreme context — the regime where the
        # quadratic attention term dominates and the window pays most
        # (2.16x over full causal when introduced; MFU is the honest
        # windowed-FLOPs ratio, so it DROPS while tokens/s rises)
        secondary("seq32k_win4k", T.PRESETS["small"].scaled(
            remat=True, remat_policy="attn", attn_window=4096),
            1, 32768, 5, key=9)
        # ring-attention flash-chunk arm (cp=1 degenerate, 2 chunks on one
        # chip): runs flash_attention_with_lse + the logsumexp hop merge —
        # the exact per-hop compute of the cp ring — on real hardware, and
        # checks it against the monolithic kernel. Reported as fwd+bwd
        # tokens/s so the differentiated-lse path is exercised too.
        out.update(_ring_flash_arm())
        # serving-shape decode: a cache padded to realistic serving
        # max_len (2k / 8k) with a short generated length — the arm the
        # length-aware block-wise cache attention exists for. Cost should
        # be ~flat in max_len (vs linear for the dense full-cache read,
        # recorded as the contrast).
        out.update(_serving_decode_arm(cfg))
        # continuous batching at mixed generation budgets: step
        # utilization (useful tokens per slot-step) vs the static-batch
        # baseline that rides every batch to its longest request, with
        # the pipelined (double-buffered dispatch) loop against the
        # sequential contrast.
        out.update(_continuous_batching_arm(cfg))
        # admission latency: bucketed+batched admission (one program per
        # power-of-two length bucket, one dispatch per freed-slot wave)
        # vs the per-length per-row path it replaced.
        out.update(_admission_arm(cfg))
        # metrics-plane overhead: the serve loop is instrumented
        # unconditionally (runtime/metrics.py), so this arm pins that
        # registry observations stay within noise — instrumented vs
        # NullRegistry serve on the same workload, plus a hard assert
        # that per-sync observation cost is < 1% of chunk wall.
        out.update(_metrics_overhead_arm(cfg))
        # tracing-plane overhead: the serve engine opens TTFT-
        # decomposition spans per request (runtime/tracing.py), so this
        # arm pins sampled-on tracing within the same budget discipline
        # as the metrics arm (< 1% of chunk wall, A/B within noise) and
        # asserts the exported trace is schema-valid Chrome trace JSON.
        out.update(_trace_overhead_arm(cfg))
        # speculative decoding with a GENUINELY smaller draft: both models
        # are first trained on a learnable sequence so the draft actually
        # predicts the target (acceptance is what buys wall-clock; with a
        # random draft speculation is a correctness demo only).
        out.update(_speculative_arm())

    # job bring-up wall against the fake gcloud fleet: cold 4-gang launch
    # parallel vs the serial baseline (max-of-gangs vs sum-of-gangs), and
    # the warm-restart wall where surviving slices are adopted and the
    # content-stamp probe skips the tarball ship entirely. Hardware-free.
    out.update(_launch_arm())

    # elastic recovery: the same injected gang kill absorbed by the
    # degraded-resume loop (survivors resync + resume; the lost gang
    # regrows) vs the stop-the-world session re-run. Hardware-free and
    # jax-free (fake trainer): the numbers measure ORCHESTRATION — loss
    # detection, resync, relaunch — not model compile walls.
    out.update(_elastic_arm())

    # coordinator crash recovery: SIGKILL the coordinator mid-train and
    # let journal replay re-adopt the live executors (user processes
    # never stop, zero re-provisions) vs the cold full-job restart the
    # journal-less stack pays — resubmit, re-provision, re-run every
    # step since the last checkpoint. Hardware-free and jax-free; the
    # recovery number is the coordinator's own recovery-wall gauge and
    # the ratio is pinned >= 3x (tests/test_recovery.py runs the arm).
    out.update(_recovery_arm())

    # goodput ledger: interval-accounting overhead vs a NullRegistry
    # ledger (< 1% of the measured step wall asserted inside the arm)
    # plus goodput_fraction_train read off a real local-backend run's
    # final GOODPUT jhist event — the job page's headline number.
    # Hardware-free and jax-free.
    out.update(_goodput_arm())

    # tonylint full-repo analysis wall: the static gate must stay cheap
    # enough to run in tier-1 on every PR (< 10 s asserted inside the
    # arm), and the shipped tree must carry zero non-baselined findings.
    # Hardware-free and jax-free.
    out.update(_lint_arm())

    # cluster daemon: back-to-back 3-job turnover through the warm
    # slice pool (digest-affinity ALREADY_EXISTS adoption) vs cold
    # sequential bring-up. Real daemon + oracle jobs, no hardware; the
    # tier-1 pin (tests/test_cluster.py) asserts
    # sched_warm_turnover_vs_cold >= 2.
    out.update(_sched_arm())

    # streaming serving data plane: the persistent token-push wire vs a
    # request/response round trip per chunk, through an injected-latency
    # transport (LatencyProxy). Deterministic: a tiny CPU model with a
    # fixed per-sync fetch floor standing in for device compute, so the
    # ratio measures TRANSPORT shape, not rig noise. The tier-1 pin
    # (tests/test_serving.py) asserts stream-vs-rr >= 2 at a 50 ms round
    # trip and streamed wall within 1.15x of the zero-delay wall.
    out.update(_streaming_arm())

    # disaggregated prefill/decode: a prefill gang ships KV packages to
    # a decode gang over a tensor channel, so concurrent admissions
    # never stall in-flight decode chunks — decode ITL p99 under
    # admission churn vs the colocated engine at equal slots, with
    # token-identical output asserted. Deterministic: injected prefill/
    # decode compute floors (the streaming arm's technique); tier-1
    # pins serving_disagg_itl_p99_vs_colocated >= 2 and the handoff
    # wall visible on the metrics plane (tests/test_disagg.py).
    out.update(_disagg_arm())

    # live fleet operations on the simulated fleet: drain the most-
    # loaded replica under concurrent streams; every migrated session's
    # tokens are checked against the sim oracle, so the migration
    # dup/drop gap is an exact count (== 0 tier-1-pinned,
    # tests/test_fleet.py) and the drain wall is bounded by placement
    # latency, not stream length.
    out.update(_fleet_arm())

    # SLO-tiered serving: identical 2x-overload open-loop mixed
    # workload with and without QoS classes; interactive p99 TTFT
    # holds under priority admission + batch-row preemption while the
    # classless FIFO baseline blows through it (ratio >= 2
    # tier-1-pinned) and every preemption eviction resumes
    # token-identically (gap == 0 tier-1-pinned, tests/test_qos.py).
    out.update(_qos_arm())

    # warm scale-up: content-addressed weights shipped peer-to-peer
    # over the channel plane vs cold storage load + retrace, plus the
    # 8-replica rolling upgrade as one seed load + O(log N) fan-out vs
    # N serial loads. Tier-1 pins warm_vs_cold >= 2 and the wave count
    # (tests/test_weightstore.py).
    out.update(_weight_ship_arm())

    # prefix-aware routing + shared KV prefix tier: sessions placed
    # where the prefix KV already lives (one replica computes the
    # prefix once, the other warms in one template ship), suffix-only
    # admission vs prefix-blind full prefill at 8x prefix reuse.
    # Deterministic: a prefill floor per forward token + fetch floors;
    # tier-1 pins serving_prefix_ttft_vs_blind >= 2 and the FLOPs
    # reduction (tests/test_prefix.py).
    out.update(_prefix_arm())

    # cross-slice MPMD pipeline: the overlapped 1F1B schedule (channel
    # sends ride the bounded window while the device computes the next
    # microbatch) vs serialized stage execution (every tensor hop waits
    # for its delivery ack) through an injected-DCN-latency transport.
    # Deterministic: tiny stage blocks + fixed compute floors; the
    # tier-1 pin (tests/test_channels.py) asserts overlap >= 1.5x.
    out.update(_pipeline_arm())

    # DCN bytes as a resource: int8 wire codec bytes ratio + interleaved
    # (v=2) vs flat placement walls under injected latency. Tier-1 pins:
    # bytes >= 1.9x, interleaved beats flat (tests/test_channels.py).
    out.update(_pipeline_dcn_arm())

    # device-prefetched vs synchronous train feed: with nonzero decode
    # cost the pipelined loop's step wall should approach the
    # pure-compute wall (decode + H2D overlap the device step) while the
    # synchronous loop pays decode + compute serially; the data-wait
    # histogram is the direct input-boundedness signal. Runs on both
    # backends (the overlap claim is transport-independent).
    out.update(_input_pipeline_arm(cfg, batch, seq,
                                   steps=20 if on_tpu else 10))

    print(json.dumps(out))


def _input_pipeline_arm(cfg, batch, seq, steps: int = 20):
    """Prefetched vs synchronous train feed (the train-path twin of the
    serve loop's pipelined-vs-sequential arm).

    Three loops over the SAME jitted step and batch shape:

    - pure-compute: one preassembled device batch re-fed every step — the
      floor the pipelined loop must approach;
    - synchronous: each step decodes on the host (an emulated IO/decode
      stall of 0.6x the compute wall, plus a real bytes→ndarray decode)
      then assembles/transfers inline — steady-state wall >= decode +
      compute, the pre-change ``global_batch``-inline behavior;
    - prefetched: the same source behind a depth-2 DevicePrefetcher
      driven by run_training — decode + H2D overlap device compute, so
      step wall should sit within ~1.1x of pure compute and
      ``tony_data_wait_seconds`` near zero.

    The sleep-based stall is deliberate: reader decode is IO-dominated
    (GIL released), so overlap potential is real, and the arm stays
    deterministic across rigs."""
    import numpy as np

    from tony_tpu.io.prefetch import DevicePrefetcher
    from tony_tpu.models import transformer as T
    from tony_tpu.models.loop import run_training
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)
    from tony_tpu.runtime import metrics as M

    opt = default_optimizer(lr=1e-3)
    step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg), opt)

    def fresh_state():
        return init_state(T.init_params(jax.random.PRNGKey(0), cfg), opt)

    rs = np.random.RandomState(0)
    raw = rs.randint(0, cfg.vocab_size,
                     size=(batch, seq + 1)).astype(np.int32).tobytes()

    # pure-compute floor: preassembled device batch, step in a tight loop
    tokens = jnp.asarray(np.frombuffer(raw, np.int32).reshape(batch,
                                                              seq + 1))
    dev_batch = {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}
    state = fresh_state()
    state, m = step(state, dev_batch)            # compile + warm
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, dev_batch)
    float(m["loss"])
    t_compute = (time.perf_counter() - t0) / steps

    decode_s = 0.6 * t_compute      # nonzero decode cost, under compute

    def host_batches():
        while True:
            time.sleep(decode_s)                 # emulated IO stall
            arr = np.frombuffer(raw, np.int32).reshape(batch, seq + 1)
            yield {"inputs": arr[:, :seq], "targets": arr[:, 1:]}

    def sync_batches():
        # inline decode + H2D on the step critical path (the contrast)
        for hb in host_batches():
            yield jax.tree.map(jnp.asarray, hb)

    def timed(data):
        saved = M.set_default(M.MetricsRegistry())
        try:
            st = fresh_state()
            st, wm = step(st, dev_batch)         # warm (same shapes/jit)
            float(wm["loss"])
            t0 = time.perf_counter()
            st, wm = run_training(step, st, data, steps)
            float(wm["loss"])
            wall = (time.perf_counter() - t0) / steps
            wait = M.get_default().histogram("tony_data_wait_seconds").sum
        finally:
            M.set_default(saved)
        return wall, wait

    t_sync, wait_sync = timed(sync_batches())
    t_pre, wait_pre = timed(DevicePrefetcher(host_batches(), depth=2))

    return {
        "train_feed_compute_ms_per_step": round(t_compute * 1e3, 2),
        "train_feed_decode_ms_per_batch": round(decode_s * 1e3, 2),
        "train_feed_sync_ms_per_step": round(t_sync * 1e3, 2),
        "train_feed_prefetch_ms_per_step": round(t_pre * 1e3, 2),
        # <= 1.1 = pipelined feed reaches the pure-compute floor
        "train_feed_prefetch_vs_compute": round(t_pre / t_compute, 3),
        # ~1 + decode share (1.6 here) = synchronous feed pays serially
        "train_feed_sync_vs_compute": round(t_sync / t_compute, 3),
        "train_feed_data_wait_s_sync": round(wait_sync, 4),
        # ~0 = the prefetcher stays ahead of the step loop
        "train_feed_data_wait_s_prefetch": round(wait_pre, 4),
    }


def _launch_arm(num_gangs: int = 4, create_delay_s: float = 0.6,
                scp_delay_s: float = 0.3) -> dict:
    """Job bring-up wall: parallel gang launch + content-addressed staging.

    Drives the REAL TpuSliceBackend against the fake gcloud (tests/
    fake_gcloud.py) with injected per-gang latency D on slice creation
    (plus a smaller scp delay), the hermetic stand-in for the minutes
    real `gcloud create` + scp staging take. Three measurements:

    - cold serial: one launch_task at a time — the pre-change
      schedule_tasks behavior, wall ~= num_gangs * (D + stage);
    - cold parallel: all gangs in flight at once (what the coordinator's
      launch pool now does), wall ~= D + stage — the acceptance bound is
      < 2*D for 4 gangs;
    - warm restart: a FRESH backend over the surviving fleet (the
      coordinator-relaunch case) — create fails fast with ALREADY_EXISTS
      and the slice is adopted, the stage digest probe matches, and ZERO
      tarballs ship (`launch_warm_stage_skip` pins that).

    The deterministic tier-1 / slow test variants live in
    tests/test_launch.py and call this function with scaled delays."""
    import concurrent.futures
    import os
    import shutil
    import sys
    import tempfile

    from tony_tpu.backend.base import LaunchSpec
    from tony_tpu.backend.tpu import TpuSliceBackend
    from tony_tpu.conf.config import TonyConfig

    repo = os.path.dirname(os.path.abspath(__file__))
    fake = os.path.join(repo, "tests", "fake_gcloud.py")
    tmp = tempfile.mkdtemp(prefix="tony-launch-bench-")
    bindir = os.path.join(tmp, "bin")
    os.makedirs(bindir)
    gcloud = os.path.join(bindir, "gcloud")
    with open(gcloud, "w") as f:
        f.write(f"#!/bin/bash\nexec {sys.executable} {fake} \"$@\"\n")
    os.chmod(gcloud, 0o755)
    job_dir = os.path.join(tmp, "job")
    log_dir = os.path.join(job_dir, "logs")
    os.makedirs(log_dir)
    with open(os.path.join(job_dir, "tony-final.xml"), "w") as f:
        f.write("<configuration></configuration>\n")

    saved_env = {k: os.environ.get(k) for k in
                 ("PATH", "FAKE_GCLOUD_ROOT", "FAKE_NUM_WORKERS",
                  "FAKE_DELAY_CREATE_S", "FAKE_DELAY_SCP_S")}
    os.environ["PATH"] = f"{bindir}:{os.environ['PATH']}"
    os.environ["FAKE_NUM_WORKERS"] = "1"
    os.environ["FAKE_DELAY_CREATE_S"] = str(create_delay_s)
    os.environ["FAKE_DELAY_SCP_S"] = str(scp_delay_s)

    conf = TonyConfig({
        "tony.scheduler.backend": "tpu",
        "tony.tpu.project": "bench", "tony.tpu.zone": "z",
        "tony.tpu.accelerator-type": "v5litepod",
        "tony.worker.instances": str(num_gangs),
        "tony.worker.slices": str(num_gangs),
    })

    def specs():
        return [LaunchSpec(task_id=f"worker:{i}", command="true", env={},
                           log_dir=log_dir, cwd=job_dir, tpu_topology="2x4")
                for i in range(num_gangs)]

    def scp_count(fleet):
        path = os.path.join(fleet, "calls.log")
        if not os.path.exists(path):
            return 0
        return sum(1 for line in open(path)
                   if line.split()[3:4] == ["scp"])

    def launch_all(backend, parallel):
        t0 = time.perf_counter()
        if parallel:
            with concurrent.futures.ThreadPoolExecutor(num_gangs) as pool:
                list(pool.map(backend.launch_task, specs()))
        else:
            for s in specs():
                backend.launch_task(s)
        return time.perf_counter() - t0

    try:
        serial_fleet = os.path.join(tmp, "fleet-serial")
        os.makedirs(serial_fleet)
        os.environ["FAKE_GCLOUD_ROOT"] = serial_fleet
        serial_b = TpuSliceBackend(conf, app_id="bench")
        serial_wall = launch_all(serial_b, parallel=False)
        serial_b.stop()

        fleet = os.path.join(tmp, "fleet")
        os.makedirs(fleet)
        os.environ["FAKE_GCLOUD_ROOT"] = fleet
        cold_b = TpuSliceBackend(conf, app_id="bench")
        cold_wall = launch_all(cold_b, parallel=True)
        cold_b.kill_all()            # NOT stop(): the fleet must survive

        # warm restart: a fresh backend (new coordinator attempt) over the
        # surviving fleet
        ships_before = scp_count(fleet)
        warm_b = TpuSliceBackend(conf, app_id="bench")
        warm_wall = launch_all(warm_b, parallel=True)
        warm_ships = scp_count(fleet) - ships_before
        warm_b.stop()
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "launch_gangs": num_gangs,
        "launch_gang_delay_s": create_delay_s,
        "launch_cold_serial_wall_s": round(serial_wall, 2),
        "launch_cold_parallel_wall_s": round(cold_wall, 2),
        # ~num_gangs when bring-up is delay-dominated (the win)
        "launch_cold_wall_vs_serial": round(serial_wall / cold_wall, 2),
        "launch_warm_wall_s": round(warm_wall, 2),
        # 1 = the stamp probe matched on every gang: zero tarball ships
        "launch_warm_stage_skip": int(warm_ships == 0),
        "launch_warm_vs_cold": round(cold_wall / max(warm_wall, 1e-9), 2),
    }


def _elastic_arm(steps: int = 16, step_wait: float = 0.15,
                 kill_at: int = 4, ckpt_every: int = 2) -> dict:
    """Elastic degraded-resume vs stop-the-world session re-run, for the
    SAME injected gang kill.

    Two local-backend jobs (2 workers × 2 gangs) run the jax-free fake
    trainer (tests/fixtures/fake_elastic_trainer.py — fixed step cadence,
    atomic progress checkpoints, marker-gated self-kill at ``kill_at``):

    - **elastic**: tony.elastic.enabled — the lost gang detaches, the
      survivor resyncs over the bumped cluster epoch and resumes from its
      progress file, and the gang regrows in the background;
    - **restart**: the pre-existing behavior — the preemption fails the
      session, everything is killed, and the session re-runs from the
      preemption budget (both workers resume from their progress files).

    Emitted keys: ``elastic_recovery_wall_s`` (jhist ELASTIC_SHRINK →
    ELASTIC_RESUMED), ``elastic_steps_replayed`` /
    ``restart_steps_replayed`` (step lines re-executed after the kill —
    work lost to the recovery strategy), and
    ``elastic_goodput_vs_restart`` (unique-steps-per-wall ratio; > 1
    means the elastic path retained more goodput for the identical kill;
    the gap widens enormously on real TPUs where stop-the-world re-pays
    slice provisioning). The deterministic tier-1 variant lives in
    tests/test_elastic.py."""
    import os
    import re
    import shutil
    import sys
    import tempfile

    from tony_tpu.client.client import TonyClient
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.events.events import find_job_files, parse_events

    repo = os.path.dirname(os.path.abspath(__file__))
    trainer = os.path.join(repo, "tests", "fixtures",
                           "fake_elastic_trainer.py")
    tmp = tempfile.mkdtemp(prefix="tony-elastic-bench-")

    def run_one(name: str, elastic: bool) -> dict:
        root = os.path.join(tmp, name)
        os.makedirs(root)
        marker = os.path.join(root, "kill.marker")
        cmd = (f"{sys.executable} {trainer} --steps {steps} "
               f"--ckpt {os.path.join(root, 'progress')} "
               f"--ckpt_every {ckpt_every} --step_wait {step_wait} "
               f"--kill {marker}:{kill_at}:1")
        conf = TonyConfig({
            "tony.staging.dir": os.path.join(root, "staging"),
            "tony.history.location": os.path.join(root, "hist"),
            "tony.application.timeout": "120000",
            "tony.worker.instances": "2",
            "tony.worker.slices": "2",
            # fast epoch fan-out so the recovery number measures the
            # machinery, not the default 1s heartbeat cadence
            "tony.task.heartbeat-interval-ms": "250",
            "tony.elastic.enabled": "true" if elastic else "false",
            "tony.elastic.regrow": "true",
            "tony.elastic.regrow-backoff-ms": "300",
        })
        client = TonyClient(conf, cmd, shell_env={
            "TEST_PREEMPT_TASKS": f"worker:1@{marker}",
            "TONY_RESYNC_KILL_GRACE_S": "3",
        })
        t0 = time.perf_counter()
        rc = client.run()
        wall = time.perf_counter() - t0
        assert rc == 0, f"{name} bench job failed"
        total = unique = 0
        log_dir = os.path.join(client.job_dir, "logs")
        for fn in os.listdir(log_dir):
            if fn.startswith("worker-") and fn.endswith(".stdout"):
                found = re.findall(r"^step (\d+)$",
                                   open(os.path.join(log_dir, fn)).read(),
                                   re.M)
                total += len(found)
                unique += len(set(found))
        recovery = None
        events = list(parse_events(find_job_files(
            conf.get("tony.history.location"))[0]))
        shrink = [e.timestamp for e in events
                  if e.event_type == "ELASTIC_SHRINK"]
        resumed = [e for e in events if e.event_type == "ELASTIC_RESUMED"]
        if resumed:
            recovery = resumed[-1].payload.get("recovery_wall_s")
        return {"wall": wall, "replayed": total - unique,
                "unique": unique, "recovery": recovery,
                "shrinks": len(shrink)}

    try:
        el = run_one("elastic", elastic=True)
        rs = run_one("restart", elastic=False)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    assert el["shrinks"] >= 1, "elastic arm never shrank"
    return {
        "elastic_kill_at_step": kill_at,
        "elastic_recovery_wall_s": round(el["recovery"] or 0.0, 3),
        "elastic_wall_s": round(el["wall"], 2),
        "restart_wall_s": round(rs["wall"], 2),
        "elastic_steps_replayed": el["replayed"],
        "restart_steps_replayed": rs["replayed"],
        # unique steps per wall second, elastic vs stop-the-world — the
        # goodput retained for the identical injected kill
        "elastic_goodput_vs_restart": round(
            (el["unique"] / el["wall"]) / (rs["unique"] / rs["wall"]), 2),
    }


def _recovery_arm(steps: int = 36, step_wait: float = 0.25,
                  kill_at: int = 4, ckpt_every: int = 2) -> dict:
    """Coordinator crash recovery (journal re-adoption) vs the cold
    full-job restart the journal-less stack pays for the SAME loss.

    Two local-backend jobs (2 workers) run the jax-free fake trainer
    (tests/fixtures/fake_elastic_trainer.py):

    - **recover**: the chaos path — worker 0 touches a marker at
      ``kill_at``, the backend SIGKILLs the coordinator mid-train, the
      client relaunches it (tony.am.retry-count) on the same job dir,
      and journal replay re-adopts the still-running executors: the
      user processes never stop, zero steps replay, and the headline
      number is the coordinator's own
      ``tony_coordinator_recovery_seconds`` gauge (restart → last
      adopted executor re-attached), read back from the final
      ``METRICS_SNAPSHOT``;
    - **cold**: the pre-journal behavior for the identical loss — the
      whole job is resubmitted and re-runs from the last committed
      checkpoint (a fresh job dir primed with the kill-step progress
      files): full bring-up + every remaining step re-executed +
      teardown.

    Emitted keys: ``coordinator_recovery_wall_s`` (the gauge),
    ``cold_restart_wall_s``, both sides' replayed/re-run step counts,
    and ``recovery_vs_cold_restart`` (cold/recovery, pinned >= 3 —
    slice re-adoption doing the work; the gap widens enormously on real
    TPUs, where the cold path also re-pays minutes of slice
    provisioning while re-adoption pays one probe). The deterministic
    tier-1 chaos variant lives in tests/test_recovery.py."""
    import os
    import re
    import shutil
    import sys
    import tempfile

    from tony_tpu.client.client import TonyClient
    from tony_tpu.cluster import journal as journal_mod
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.events.events import find_job_files, parse_events

    repo = os.path.dirname(os.path.abspath(__file__))
    trainer = os.path.join(repo, "tests", "fixtures",
                           "fake_elastic_trainer.py")
    tmp = tempfile.mkdtemp(prefix="tony-recovery-bench-")
    workers = 2

    def run_one(name, kill_flags="", extra_conf=None, shell_env=None):
        root = os.path.join(tmp, name)
        os.makedirs(root, exist_ok=True)
        cmd = (f"{sys.executable} {trainer} --steps {steps} "
               f"--ckpt {os.path.join(root, 'progress')} "
               f"--ckpt_every {ckpt_every} --step_wait {step_wait}"
               + (f" {kill_flags}" if kill_flags else ""))
        conf = TonyConfig(dict({
            "tony.staging.dir": os.path.join(root, "staging"),
            "tony.history.location": os.path.join(root, "hist"),
            "tony.application.timeout": "180000",
            "tony.worker.instances": str(workers),
            "tony.task.heartbeat-interval-ms": "250",
            "tony.metrics.snapshot-interval-ms": "1000",
        }, **(extra_conf or {})))
        client = TonyClient(conf, cmd, shell_env=shell_env or {})
        t0 = time.perf_counter()
        rc = client.run()
        wall = time.perf_counter() - t0
        assert rc == 0, f"{name} bench job failed (job dir {client.job_dir})"
        total = unique = 0
        log_dir = os.path.join(client.job_dir, "logs")
        for fn in os.listdir(log_dir):
            if fn.startswith("worker-") and fn.endswith(".stdout"):
                found = re.findall(r"^step (\d+)$",
                                   open(os.path.join(log_dir, fn)).read(),
                                   re.M)
                total += len(found)
                unique += len(set(found))
        return client, wall, total - unique

    # recover: SIGKILL the coordinator once worker 0 starts `kill_at`
    marker = os.path.join(tmp, "recover", "kill.marker")
    os.makedirs(os.path.dirname(marker))
    try:
        client, recover_wall, recover_replayed = run_one(
            "recover", kill_flags=f"--kill {marker}:{kill_at}:0",
            extra_conf={"tony.am.retry-count": "1"},
            shell_env={"TEST_KILL_COORDINATOR": marker})
        assert os.path.exists(marker + ".fired"), "kill hook never fired"
        records = journal_mod.replay(
            journal_mod.journal_path(client.job_dir))
        state = journal_mod.fold(records)
        assert state.incarnation == 2, "coordinator never restarted"
        launches = [r for r in records if r["k"] == "launch"]
        assert len(launches) == workers, "recovery re-provisioned a task"
        # the recovery wall rides am:0 into the restarted generation's
        # final METRICS_SNAPSHOT
        recovery_wall = None
        for f in find_job_files(os.path.join(tmp, "recover", "hist")):
            events = list(parse_events(f))
            if not any(e.event_type == "COORDINATOR_RESTART"
                       for e in events):
                continue
            snaps = [e for e in events
                     if e.event_type == "METRICS_SNAPSHOT"]
            for name, _, value in snaps[-1].payload["tasks"]["am:0"]["g"]:
                if name == "tony_coordinator_recovery_seconds":
                    recovery_wall = value
        assert recovery_wall, "recovery wall gauge never recorded"

        # cold: a fresh submission primed with the kill-step checkpoints
        # — everything re-provisions and every later step re-runs
        primed = (kill_at // ckpt_every) * ckpt_every
        cold_root = os.path.join(tmp, "cold")
        os.makedirs(cold_root)
        for i in range(workers):
            with open(os.path.join(cold_root,
                                   f"progress-worker-{i}"), "w") as f:
                f.write(str(primed))
        _, cold_wall, _ = run_one("cold")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    ratio = cold_wall / max(recovery_wall, 1e-9)
    assert ratio >= 3, (
        f"coordinator recovery ({recovery_wall:.2f}s) not >= 3x better "
        f"than the cold full-job restart ({cold_wall:.2f}s)")
    return {
        "recovery_kill_at_step": kill_at,
        "coordinator_recovery_wall_s": round(recovery_wall, 3),
        # 0: re-adopted trainers never stopped, so nothing re-ran
        "recovery_steps_replayed": recover_replayed,
        "recovery_job_wall_s": round(recover_wall, 2),
        "cold_restart_wall_s": round(cold_wall, 2),
        "cold_restart_steps_rerun": steps - primed,
        "recovery_vs_cold_restart": round(ratio, 2),
    }


def _goodput_arm(steps: int = 12, step_wait: float = 0.1) -> dict:
    """Goodput-ledger overhead + a real attributed training run.

    (a) Microbench: one enter/exit interval through the ledger, mirrored
    into a live MetricsRegistry vs a NullRegistry (the metrics arm's A/B
    discipline — snapshot-per-"step" included so the mirror path is in
    the measurement). The train loop opens <= 4 intervals per step
    (data_wait / step / checkpoint / eval), so 4x the per-interval cost
    is asserted < 1% of the REAL mean step wall measured in (b) — the
    issue's hard bound: attribution must be free.

    (b) A 2-worker local-backend run of the jax-free fake trainer whose
    final (cumulative) GOODPUT jhist event yields
    ``goodput_fraction_train`` — the same headline the history job page
    renders — plus the mean step wall used by (a)'s bound.

    Emitted keys: ``goodput_interval_ns``, ``goodput_ledger_frac_of_step``
    (< 0.01 asserted), ``goodput_ledger_live_vs_null`` (~1.0),
    ``goodput_fraction_train``, ``goodput_step_wall_mean_s``."""
    import os
    import shutil
    import sys
    import tempfile

    from tony_tpu.client.client import TonyClient
    from tony_tpu.conf.config import TonyConfig
    from tony_tpu.events.events import find_job_files, parse_events
    from tony_tpu.runtime import goodput as goodput_mod
    from tony_tpu.runtime import metrics as M

    # (a) per-interval cost through the real enter/exit path; a snapshot
    # every `per_snap` intervals models the trainer's publish cadence
    n, per_snap = 100_000, 100

    def timed(reg) -> float:
        led = goodput_mod.GoodputLedger(registry=reg)
        t0 = time.perf_counter()
        for i in range(n):
            with led.enter("step"):
                pass
            if i % per_snap == 0:
                led.snapshot()
        return (time.perf_counter() - t0) / n

    live = timed(M.MetricsRegistry())
    null = timed(M.NullRegistry())

    # (b) the real run: step walls + the headline fraction from the
    # final GOODPUT event
    tmp = tempfile.mkdtemp(prefix="tony-goodput-bench-")
    repo = os.path.dirname(os.path.abspath(__file__))
    trainer = os.path.join(repo, "tests", "fixtures",
                           "fake_elastic_trainer.py")
    try:
        cmd = (f"{sys.executable} {trainer} --steps {steps} "
               f"--ckpt {os.path.join(tmp, 'progress')} "
               f"--ckpt_every 2 --step_wait {step_wait} --tail_wait 0:1.5")
        conf = TonyConfig({
            "tony.staging.dir": os.path.join(tmp, "staging"),
            "tony.history.location": os.path.join(tmp, "hist"),
            "tony.application.timeout": "120000",
            "tony.worker.instances": "2",
            "tony.task.heartbeat-interval-ms": "100",
            "tony.metrics.snapshot-interval-ms": "300",
        })
        rc = TonyClient(conf, cmd).run()
        assert rc == 0, "goodput bench job failed"
        final = None
        for f in find_job_files(os.path.join(tmp, "hist")):
            for e in parse_events(f):
                if e.event_type == "GOODPUT":
                    final = e
        assert final is not None, "no GOODPUT event reached the jhist"
        fraction = final.payload["fraction"]
        assert 0 < fraction <= 1, fraction
        sw_c = sw_s = 0.0
        for tid, entry in final.payload["tasks"].items():
            if tid.startswith("worker:"):
                sw_c += entry["sw"]["c"]
                sw_s += entry["sw"]["s"]
        assert sw_c >= 2 * steps, "trainer ledgers never reached the jhist"
        step_wall = sw_s / sw_c
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # the hard bound: <= 4 ledger intervals per train step must cost
    # < 1% of the step wall they attribute
    frac = 4 * live / step_wall
    assert frac < 0.01, (
        f"goodput ledger costs {frac:.2%} of the step wall — interval "
        f"accounting is no longer free on the train loop")
    return {
        "goodput_interval_ns": round(live * 1e9, 1),
        "goodput_ledger_frac_of_step": round(frac, 6),
        "goodput_ledger_live_vs_null": round(live / max(null, 1e-12), 3),
        "goodput_fraction_train": round(fraction, 4),
        "goodput_step_wall_mean_s": round(step_wall, 4),
    }


def _lint_arm() -> dict:
    """tonylint full-repo analysis wall (docs/static-analysis.md).

    Runs every checker — per-file AST passes over all of tony_tpu/ plus
    the repo-wide proto/frame/observability checks — and asserts the
    whole sweep lands under 10 s, the budget that keeps the gate cheap
    enough for tier-1 (tests/test_lint.py runs the same self-check).
    Also asserts the shipped tree is clean: zero findings outside the
    committed ratchet baseline.

    Emitted keys: ``lint_full_repo_s`` (< 10 asserted),
    ``lint_files_scanned``, ``lint_findings_unbaselined`` (== 0
    asserted), ``lint_baseline_entries``."""
    import os

    from tony_tpu.devtools import lint

    pkg = os.path.join(lint.REPO_ROOT, "tony_tpu")
    t0 = time.perf_counter()
    findings = lint.run([pkg])
    wall = time.perf_counter() - t0
    left, _suppressed, _stale = lint.apply_baseline(
        findings, lint.load_baseline(
            os.path.join(lint.REPO_ROOT, lint.DEFAULT_BASELINE)))
    n_files = len(lint.scan_paths([pkg]))
    assert wall < 10.0, f"tonylint full sweep took {wall:.1f}s (>= 10s)"
    assert not left, "tonylint found unbaselined findings:\n" + \
        "\n".join(f.render() for f in left)
    return {
        "lint_full_repo_s": round(wall, 3),
        "lint_files_scanned": n_files,
        "lint_findings_unbaselined": len(left),
        "lint_baseline_entries": len(lint.load_baseline(
            os.path.join(lint.REPO_ROOT, lint.DEFAULT_BASELINE))),
    }


def _sched_arm(n_jobs: int = 3, duration_steps: int = 40,
               steps_per_s: float = 1000.0,
               cold_bringup_s: float = 0.30,
               warm_adopt_s: float = 0.02) -> dict:
    """Cluster-daemon warm-pool turnover vs cold sequential bring-up
    (docs/cluster.md §Warm-pool affinity).

    Two identical 3-job back-to-back workloads through a real
    :class:`~tony_tpu.cluster.daemon.ClusterDaemon` (OracleRunner, a
    2-slice pool, every job a 2-slice gang, all submitted at once so
    the pool turns over between them).  WARM: all jobs share one
    staging digest, so jobs 2..n adopt the digest-tagged slices the
    previous job freed (ALREADY_EXISTS warm adoption).  COLD: distinct
    digests — the no-affinity contrast — so every job pays full
    bring-up.  Turnover is the completion-to-completion gap (bring-up +
    run); the bring-up constants are PR 4's measured 9.1s-vs-0.49s
    contrast scaled down to keep the arm under a second.

    Emitted keys: ``sched_warm_turnover_s``, ``sched_cold_turnover_s``,
    ``sched_warm_turnover_vs_cold`` (pinned >= 2 in
    tests/test_cluster.py), ``sched_queue_wait_p99_s`` (bucket-
    interpolated from tony_sched_queue_wait_seconds via
    histogram_quantile), ``sched_warm_hits``."""
    import tempfile

    from tony_tpu.cluster.daemon import ClusterDaemon, OracleRunner
    from tony_tpu.runtime.metrics import MetricsRegistry, \
        histogram_quantile

    def run_arm(warm_affinity: bool) -> tuple[float, MetricsRegistry]:
        registry = MetricsRegistry()
        runner = OracleRunner(cold_bringup_s=cold_bringup_s,
                              warm_adopt_s=warm_adopt_s)
        daemon = ClusterDaemon(
            tempfile.mkdtemp(prefix="tony-sched-bench-"),
            slices=2, runner=runner, registry=registry,
            tick_interval_s=0.005)
        daemon.start()
        try:
            ids = []
            for i in range(n_jobs):
                digest = "bench-dd" if warm_affinity else f"bench-{i}"
                ids.append(daemon.handle_op({
                    "op": "submit", "user": "bench", "slices": 2,
                    "digest": digest,
                    "payload": {"duration_steps": duration_steps,
                                "steps_per_s": steps_per_s}})["job_id"])
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                states = {j["job_id"]: j["state"]
                          for j in daemon.handle_op({"op": "list"})["jobs"]}
                if all(states[i] == "COMPLETED" for i in ids):
                    break
                time.sleep(0.005)
            finished = sorted(daemon.sched.jobs[i].finished_at
                              for i in ids)
            assert all(daemon.sched.jobs[i].state == "COMPLETED"
                       for i in ids), f"bench jobs did not finish: {states}"
            gaps = [b - a for a, b in zip(finished, finished[1:])]
            return sum(gaps) / len(gaps), registry
        finally:
            daemon.stop()

    warm_turnover, registry = run_arm(warm_affinity=True)
    cold_turnover, _ = run_arm(warm_affinity=False)
    hist = registry.histogram("tony_sched_queue_wait_seconds")
    p99 = histogram_quantile(hist, 0.99)
    warm_hits = registry.counter("tony_pool_warm_hits_total").value
    return {
        "sched_warm_turnover_s": round(warm_turnover, 4),
        "sched_cold_turnover_s": round(cold_turnover, 4),
        "sched_warm_turnover_vs_cold": round(
            cold_turnover / max(warm_turnover, 1e-9), 2),
        "sched_queue_wait_p99_s": round(p99, 4),
        "sched_warm_hits": int(warm_hits),
    }


def _streaming_arm(slots: int = 3, n_req: int = 6, prompt_len: int = 8,
                   budget: int = 64, chunk: int = 4,
                   round_trip_s: float = 0.05,
                   fetch_floor_s: float = 0.02) -> dict:
    """Streamed (persistent token-push) serving vs the per-chunk
    request/response tunnel, under an injected transport round trip D.

    Three runs of the SAME workload, identical tokens asserted across
    all three:

    - **streamed, zero delay**: the floor. ServingServer pushes TOKENS
      frames as each chunk is consumed; client threads drain them off
      one multiplexed connection.
    - **streamed through a LatencyProxy** injecting ``round_trip_s`` of
      round-trip latency: admissions and deltas pipeline through the
      link, so the whole workload pays the round trip ONCE (first admit
      half + last delta half) — wall within ~1.15x of the floor however
      many chunks flow.
    - **request/response baseline**: the same engine driven closed-batch
      and sequentially with the round trip injected INTO the control
      loop — every chunk fetch and every admission wave pays
      ``round_trip_s`` serialized with compute. That is the
      pre-streaming tunnel's cost model (BENCH_r05 measured it at
      ~70-100 ms per sync on a real tunneled chip; ROADMAP item 1 names
      it THE serving bottleneck): wall degrades by ~``(chunks +
      admission waves) x D`` while the streamed wall does not.

    Determinism: a tiny CPU model plus ``fetch_floor_s`` of injected
    per-sync fetch wall standing in for device chunk compute (the
    launch arm's fake-gcloud-delay technique), and a short PLUG request
    submitted first so the engine is provably mid-burst when the real
    admissions arrive — every run executes the same sync schedule, so
    the ratios hold on any rig. ``serving_stream_ttft_s`` is the
    CLIENT-side mean time-to-first-token under the delayed link
    (includes slot-wait for the requests beyond ``slots``). The tier-1
    and @slow test variants (tests/test_serving.py) call this function
    directly."""
    import threading

    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.client import StreamingClient
    from tony_tpu.serving.netem import LatencyProxy
    from tony_tpu.serving.server import ServingServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    class FloorFetch(ContinuousBatcher):
        """Injects a fixed per-sync fetch wall: the deterministic
        stand-in for device chunk compute."""

        def _fetch(self, handle):
            if fetch_floor_s > 0:
                time.sleep(fetch_floor_s)
            return super()._fetch(handle)

    class TunnelFetch(FloorFetch):
        """The pre-streaming tunnel: a transport round trip serialized
        into every chunk fetch and every admission wave."""

        def _fetch(self, handle):
            time.sleep(round_trip_s)
            return super()._fetch(handle)

        def _admit_batch(self, pairs, prompts):
            time.sleep(round_trip_s)
            super()._admit_batch(pairs, prompts)

    rs = np.random.RandomState(11)
    prompts = [[int(t) for t in rs.randint(0, cfg.vocab_size,
                                           size=prompt_len)]
               for _ in range(n_req)]
    max_len = prompt_len + budget
    plug_budget = 6 * chunk          # ~6 syncs of cover for admissions
    batcher = FloorFetch(params, cfg, batch=slots, max_len=max_len,
                         chunk=chunk)
    batcher.serve(prompts[:slots], [chunk] * slots)     # compile + warm

    def run_streamed(delay_rt):
        # try/finally over the whole lifecycle: a mid-arm failure must
        # not leak a live engine thread / proxy / client into the
        # calling process (the tier-1 test imports and runs this arm)
        srv = ServingServer(batcher, registry=M.MetricsRegistry())
        proxy = None
        c = None
        try:
            port = srv.start()
            if delay_rt > 0:
                proxy = LatencyProxy("127.0.0.1", port, delay_rt / 2)
                port = proxy.start()
            outs: list = [None] * n_req
            ttfts: list = [0.0] * n_req
            c = StreamingClient("127.0.0.1", port)

            def drain(i, rid, t_submit):
                toks, first = [], None
                for delta in c.deltas(rid):
                    if first is None:
                        first = time.perf_counter()
                    toks.extend(delta)
                outs[i] = toks
                ttfts[i] = (first or time.perf_counter()) - t_submit

            t0 = time.perf_counter()
            # the plug: a short request that keeps the engine mid-burst
            # while the real admissions travel, so they all land in ONE
            # settle — the open-loop schedule becomes deterministic
            plug = c.submit(prompts[0], plug_budget)
            c.next_event(plug, timeout=60)       # its first delta
            threads = []
            for i, p in enumerate(prompts):
                t_submit = time.perf_counter()
                rid = c.submit(p, budget)
                th = threading.Thread(target=drain,
                                      args=(i, rid, t_submit))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            syncs = batcher.phase_times.count("fetch")
            return wall, outs, ttfts, syncs
        finally:
            # closing the client first cancels anything still in
            # flight, so the engine abort below is instant either way
            if c is not None:
                c.close()
            if proxy is not None:
                proxy.stop()
            srv.stop()

    def run_rr():
        tb = TunnelFetch(params, cfg, batch=slots, max_len=max_len,
                         chunk=chunk, pipeline=False)
        saved = M.set_default(M.MetricsRegistry())
        try:
            tb.serve(prompts[:slots], [chunk] * slots)  # warm (cheap)
            t0 = time.perf_counter()
            outs = tb.serve(prompts, budget)
            wall = time.perf_counter() - t0
        finally:
            M.set_default(saved)
        exchanges = (tb.phase_times.count("fetch")
                     + tb.phase_times.count("admit"))
        return wall, outs, exchanges

    t_s0, outs0, _, syncs0 = run_streamed(0.0)
    t_sd, outs_d, ttfts, syncs_d = run_streamed(round_trip_s)
    t_rr, outs_rr, exchanges = run_rr()
    assert outs0 == outs_d == outs_rr, (
        "transport modes produced different tokens — wire corruption")
    return {
        "serving_stream_round_trip_s": round_trip_s,
        "serving_stream_wall_nodelay_s": round(t_s0, 3),
        "serving_stream_wall_s": round(t_sd, 3),
        # ~1.0-1.15 = the round trip is paid once, pipelined away
        "serving_stream_vs_nodelay": round(t_sd / t_s0, 3),
        "serving_stream_syncs": syncs_d,
        # the plug makes these equal — the determinism guard
        "serving_stream_syncs_nodelay": syncs0,
        "serving_rr_wall_s": round(t_rr, 3),
        "serving_rr_round_trips": exchanges,
        # the tentpole ratio: >= 2 at a 50 ms round trip (tier-1-pinned)
        "serving_stream_vs_rr_wall": round(t_rr / t_sd, 2),
        "serving_stream_ttft_s": round(sum(ttfts) / len(ttfts), 3),
    }


def _disagg_arm(slots: int = 4, n_streams: int = 2, n_admits: int = 6,
                prompt_len: int = 12, stream_budget: int = 60,
                admit_budget: int = 4, chunk: int = 2,
                prefill_floor_s: float = 0.05,
                fetch_floor_s: float = 0.015,
                one_way_s: float = 0.0) -> dict:
    """Disaggregated prefill/decode vs the colocated engine: decode
    inter-token latency under CONCURRENT ADMISSIONS, at equal slot
    count and with token-identical output (asserted).

    The colocated engine interleaves prefill and decode dispatches on
    one device queue: every admission wave's prefill
    (``prefill_floor_s`` — the injected stand-in for real prefill
    compute, tens of ms on hardware) lands between two decode chunks,
    so the live streams' inter-token gap spikes to
    ``fetch_floor_s + prefill_floor_s`` whenever anything is admitted.
    Disaggregated, the SAME floors apply — but prefill burns on the
    prefill gang while the decode gang only scatters the shipped KV
    into a freed slot, so the live streams' p99 gap stays at the
    decode floor. The workload: ``n_streams`` long streams occupy part
    of the slot pool; once all are streaming, ``n_admits`` short
    requests churn through the remaining slots.

    Deterministic: a tiny CPU model plus the injected floors dominate
    scheduling noise; both paths run the same floors, the same ladder,
    and the same greedy workload, and their outputs are asserted
    identical request-for-request. ``one_way_s`` (the @slow variant)
    additionally routes the client connection through a LatencyProxy —
    ITL is produced by push cadence, so an injected WAN hop must not
    change the p99 contrast. ``serving_disagg_handoff_wall_s`` is the
    mean prefill-side KV handoff wall (extract + serialize + channel
    send) off the ``tony_kv_ship_seconds`` histogram — the metrics
    plane's view of the handoff, which the tier-1 test also asserts
    appears in the request trace as the ``kv.ship`` span."""
    import threading

    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.client import StreamingClient
    from tony_tpu.serving.disagg import DecodeServer, PrefillServer
    from tony_tpu.serving.netem import LatencyProxy
    from tony_tpu.serving.router import ServingRouter
    from tony_tpu.serving.server import ServingServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    class FloorFetch(ContinuousBatcher):
        """Fixed per-sync fetch wall: the decode-chunk compute floor."""

        def _fetch(self, handle):
            if fetch_floor_s > 0:
                time.sleep(fetch_floor_s)
            return super()._fetch(handle)

    class ColocatedFloor(FloorFetch):
        """Colocated admission pays the prefill floor INSIDE the serve
        loop — the dispatch-interleaving cost disaggregation removes."""

        def _admit_prompts(self, pairs, prompts):
            if prefill_floor_s > 0:
                time.sleep(prefill_floor_s)
            super()._admit_prompts(pairs, prompts)

    class FloorPrefill(PrefillServer):
        """The SAME prefill floor, burned on the prefill gang."""

        def _prefill_group(self, grp, bucket, entry=None):
            if prefill_floor_s > 0:
                time.sleep(prefill_floor_s)
            super()._prefill_group(grp, bucket, entry)

    rs = np.random.RandomState(17)
    stream_prompts = [[int(t) for t in rs.randint(
        0, cfg.vocab_size, size=prompt_len)] for _ in range(n_streams)]
    admit_prompts = [[int(t) for t in rs.randint(
        0, cfg.vocab_size, size=prompt_len)] for _ in range(n_admits)]
    max_len = prompt_len + stream_budget

    def run_workload(port):
        """Streams first (wait until every one delivered a delta —
        measurement starts with the pool provably mid-decode), then the
        admission churn; returns (outputs, long-stream per-token
        gaps)."""
        outs: dict = {}
        gaps: list[float] = []
        with StreamingClient("127.0.0.1", port) as c:
            # warm every program (admit/land bucket, step chunk) so no
            # compile lands inside a measured gap
            toks, _ = c.result(c.submit(stream_prompts[0], admit_budget),
                               timeout=120)
            srids = [c.submit(p, stream_budget) for p in stream_prompts]
            events = {r: c.next_event(r, timeout=120) for r in srids}

            def drain(rid, first_ev):
                toks = list(first_ev[1])
                last = time.perf_counter()
                while True:
                    ev = c.next_event(rid, timeout=120)
                    if ev[0] == "retired":
                        break
                    assert ev[0] == "tokens", ev
                    now = time.perf_counter()
                    gaps.append((now - last) / len(ev[1]))
                    last = now
                    toks.extend(ev[1])
                outs[rid] = toks

            threads = [threading.Thread(target=drain, args=(r, events[r]))
                       for r in srids]
            for th in threads:
                th.start()
            arids = []
            for p in admit_prompts:
                arids.append(c.submit(p, admit_budget))
                time.sleep(2 * fetch_floor_s)   # churn, not one burst
            for r in arids:
                outs[r] = c.result(r, timeout=120)[0]
            for th in threads:
                th.join()
            ordered = ([outs[r] for r in srids]
                       + [outs[r] for r in arids])
        return ordered, gaps

    def p99(xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def run_colocated():
        srv = ServingServer(
            ColocatedFloor(params, cfg, batch=slots, max_len=max_len,
                           chunk=chunk),
            registry=M.MetricsRegistry())
        proxy = None
        try:
            port = srv.start()
            if one_way_s > 0:
                proxy = LatencyProxy("127.0.0.1", port, one_way_s)
                port = proxy.start()
            return run_workload(port)
        finally:
            if proxy is not None:
                proxy.stop()
            srv.stop()

    def run_disagg():
        regp = M.MetricsRegistry()
        pre = FloorPrefill(params, cfg, max_len=max_len,
                           max_batch=slots, registry=regp)
        dec = DecodeServer(
            FloorFetch(params, cfg, batch=slots, max_len=max_len,
                       chunk=chunk),
            registry=M.MetricsRegistry())
        router = ServingRouter([f"127.0.0.1:{pre.start()}"],
                               decode_replicas=[f"127.0.0.1:{dec.start()}"],
                               registry=M.MetricsRegistry())
        proxy = None
        try:
            port = router.start()
            if one_way_s > 0:
                proxy = LatencyProxy("127.0.0.1", port, one_way_s)
                port = proxy.start()
            outs, gaps = run_workload(port)
            ship = regp.histogram("tony_kv_ship_seconds")
            assert ship.count > 0, \
                "kv handoff wall missing from the metrics plane"
            return outs, gaps, ship.sum / ship.count, ship.count
        finally:
            if proxy is not None:
                proxy.stop()
            router.stop()
            pre.stop()
            dec.stop()

    outs_colo, gaps_colo = run_colocated()
    outs_dis, gaps_dis, handoff_wall, handoffs = run_disagg()
    assert outs_colo == outs_dis, (
        "disaggregated serving diverged from the colocated engine — "
        "KV shipment corruption")
    itl_colo, itl_dis = p99(gaps_colo), p99(gaps_dis)
    return {
        "serving_disagg_prefill_floor_s": prefill_floor_s,
        "serving_disagg_fetch_floor_s": fetch_floor_s,
        "serving_colocated_itl_p99_s": round(itl_colo, 4),
        "serving_disagg_itl_p99_s": round(itl_dis, 4),
        # the tentpole ratio: admissions stall colocated decode chunks
        # by the prefill floor; disaggregated decode never sees it
        # (>= 2 tier-1-pinned)
        "serving_disagg_itl_p99_vs_colocated": round(
            itl_colo / max(itl_dis, 1e-9), 2),
        "serving_disagg_handoff_wall_s": round(handoff_wall, 4),
        "serving_disagg_handoffs": handoffs,
    }


def _fleet_arm(n_replicas: int = 4, n_streams: int = 8,
               max_new: int = 80, itl_s: float = 0.003) -> dict:
    """Planned drain under live load, on the simulated fleet: SimFleet
    stands up ``n_replicas`` oracle-token replicas behind a real
    router, ``n_streams`` sessions stream concurrently, and the most-
    loaded replica is drained mid-stream. Every session's final token
    list is compared against the ``sim_token`` oracle — dup/drop
    during migration shows up as a positional mismatch, so
    ``serving_migration_token_gap`` is an exact count, pinned == 0 by
    tier-1 (tests/test_fleet.py). ``serving_drain_wall_s`` is the
    wall from fence to last migrated ACK: with migration implemented
    as re-prefill-on-survivor it is bounded by placement latency, not
    by any session's remaining stream length."""
    import threading

    from tony_tpu.runtime.metrics import MetricsRegistry
    from tony_tpu.serving.client import StreamingClient
    from tony_tpu.serving.simfleet import SimFleet, sim_token

    reg = MetricsRegistry()
    fleet = SimFleet(n_replicas, itl_s=itl_s, slots=16, registry=reg)
    outs: dict = {}

    def pump(client, rid):
        toks = []
        for delta in client.deltas(rid):
            toks.extend(delta)
        outs[rid] = toks

    try:
        port = fleet.start()
        with StreamingClient("127.0.0.1", port) as client:
            seeds = {}
            threads = []
            for i in range(n_streams):
                seed = 1000 + 17 * i
                rid = client.submit([seed, 1, 2, 3], max_new)
                seeds[rid] = seed
                t = threading.Thread(target=pump, args=(client, rid),
                                     daemon=True)
                t.start()
                threads.append(t)
            # let every stream get past first tokens so the drain
            # migrates genuinely mid-flight sessions
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                reps = client.stats()["replicas"]
                if all(s["assigned"] > 0 for s in reps.values()):
                    break
                time.sleep(0.01)
            victim = max(reps, key=lambda a: reps[a]["assigned"])
            res = client.drain_replica(victim)
            assert res.get("drained"), f"drain failed: {res}"
            for t in threads:
                t.join(timeout=60)
            gap = 0
            for rid, toks in outs.items():
                oracle = [sim_token(seeds[rid], p) for p in range(max_new)]
                gap += abs(len(toks) - max_new)
                gap += sum(1 for a, b in zip(toks, oracle) if a != b)
    finally:
        fleet.stop()
    return {
        "serving_fleet_replicas": n_replicas,
        "serving_fleet_streams": n_streams,
        "serving_drain_wall_s": round(res["wall_s"], 4),
        "serving_drain_migrated": res["migrated"],
        # dup/drop token count across every migrated session vs the
        # oracle (== 0 tier-1-pinned)
        "serving_migration_token_gap": gap,
    }


def _qos_arm(n_replicas: int = 1, slots: int = 4, itl_s: float = 0.004,
             ttft_s: float = 0.01, max_new: int = 16, n_req: int = 36,
             one_way_s: float = 0.0) -> dict:
    """SLO-tiered serving under 2x overload, on the simulated fleet.

    An open-loop mixed workload (1 interactive : 1 standard : 2 batch)
    arrives at twice the fleet's service rate — open-loop, so the
    backlog genuinely builds instead of the clients self-throttling.
    Run once with classes (interactive admissions jump the queue and
    preempt decoding batch rows) and once classless (identical
    arrivals, FIFO service): the interactive p99 TTFT ratio between
    the two runs is the tentpole number,
    ``serving_qos_interactive_ttft_p99_vs_classless`` (>= 2
    tier-1-pinned, tests/test_qos.py). Every preempted batch row is
    evicted-to-queue and later resumes via rng-offset re-prefill, so
    comparing every completed stream against the ``sim_token`` oracle
    makes ``serving_qos_preempt_token_gap`` an exact dup/drop count
    (== 0 tier-1-pinned). TTFT p99s come from
    ``histogram_quantile`` over fine-bucket local histograms — the
    same estimator the dashboards use. ``one_way_s`` (the @slow
    variant) pushes the whole workload through a LatencyProxy WAN
    hop: priority is a queue-order property, so the ratio must
    survive transport latency."""
    from tony_tpu.runtime.metrics import (MetricsRegistry,
                                          histogram_quantile)
    from tony_tpu.serving.netem import LatencyProxy
    from tony_tpu.serving.simfleet import (SimFleet, open_loop_load,
                                           sim_token)

    # 2x overload: one arrival every half mean per-request service time
    interval_s = (itl_s * max_new) / (slots * n_replicas) / 2.0
    mix = [("interactive", "standard", "batch", "batch")[i % 4]
           for i in range(n_req)]

    def run(classes):
        fleet = SimFleet(n_replicas, itl_s=itl_s, ttft_s=ttft_s,
                         slots=slots, max_queue_depth=10 * n_req,
                         registry=MetricsRegistry())
        proxy = None
        try:
            port = fleet.start()
            if one_way_s > 0:
                proxy = LatencyProxy("127.0.0.1", port, one_way_s)
                port = proxy.start()
            recs = open_loop_load(port, classes, interval_s=interval_s,
                                  max_new=max_new)
            preempts = sum(r.preemptions
                           for r in fleet.replicas.values())
        finally:
            if proxy is not None:
                proxy.stop()
            fleet.stop()
        return recs, preempts

    classed, preempts = run(mix)
    classless, _ = run([""] * n_req)

    def p99(recs, idxs):
        reg = MetricsRegistry()
        hist = reg.histogram(
            "tony_bench_qos_ttft_seconds",
            help="client-side TTFT samples for the qos arm",
            buckets=tuple(0.002 * i for i in range(1, 400)))
        for i in idxs:
            if recs[i]["ttft_s"] is not None:
                hist.observe(recs[i]["ttft_s"])
        return histogram_quantile(hist, 0.99)

    inter_idx = [i for i, c in enumerate(mix) if c == "interactive"]
    classed_p99 = p99(classed, inter_idx)
    # the SAME arrival positions in the classless run: any difference
    # is the scheduling discipline, not the arrival pattern
    classless_p99 = p99(classless, inter_idx)
    gap = 0
    for i, r in enumerate(classed):
        if r["shed"]:
            continue
        want = [sim_token(1000 + i, p) for p in range(max_new)]
        gap += abs(len(r["tokens"]) - max_new)
        gap += sum(1 for a, b in zip(r["tokens"], want) if a != b)
    return {
        "serving_qos_requests": n_req,
        "serving_qos_preemptions": preempts,
        "serving_qos_interactive_ttft_p99_s": round(classed_p99, 4),
        "serving_qos_classless_ttft_p99_s": round(classless_p99, 4),
        # classed interactive p99 holds under 2x overload while the
        # classless baseline blows through it (>= 2 tier-1-pinned)
        "serving_qos_interactive_ttft_p99_vs_classless": round(
            classless_p99 / max(classed_p99, 1e-9), 2),
        # dup/drop token count across every preemption eviction vs the
        # oracle (== 0 tier-1-pinned)
        "serving_qos_preempt_token_gap": gap,
    }


def _weight_ship_arm(n_replicas: int = 8, mb: int = 8,
                     load_s: float = 0.5, trace_s: float = 0.25,
                     ship_s: float = 0.05) -> dict:
    """Warm scale-up vs cold start, two measurements:

    1. One replica's time-to-serving: a REAL chunked weight ship over a
       localhost channel (pack -> send_bytes -> digest-verified land of
       an ``mb``-megabyte artifact) vs the cold path's injected
       storage-load + XLA-trace floors (a warmed replica lands
       pre-traced via the shipped compile cache, so it pays neither).
       Tier-1 pins ``serving_scaleup_warm_vs_cold >= 2``
       (tests/test_weightstore.py).
    2. The 8-replica rolling-upgrade wall on the simulated fleet: the
       warmer spends ONE storage load to mint a seed, then fans out in
       O(log N) ship waves (wave count pinned == 1 + ceil(log2 N)),
       vs the old path's N serial storage loads."""
    import math

    import numpy as np

    from tony_tpu.channels.channel import ChannelHub, ChannelSender
    from tony_tpu.runtime.metrics import MetricsRegistry
    from tony_tpu.serving.simfleet import SimFleet, SimProvider, SimWarmer
    from tony_tpu.serving.weightstore import (WEIGHT_CHANNEL, pack_weights,
                                              tree_digest, unpack_weights)
    from tony_tpu.serving.fleet import FleetController

    # -- 1. one replica: real ship vs injected cold floors -------------------
    rng = np.random.RandomState(7)
    params = {"layer": {"w": rng.randn(mb * 262144).astype(np.float32),
                        "b": rng.randn(256).astype(np.float32)}}
    blob = pack_weights(params, version="bench")
    reg = MetricsRegistry()
    hub = ChannelHub(registry=reg)
    port = hub.start()
    recv = hub.receiver(WEIGHT_CHANNEL)
    try:
        sender = ChannelSender(f"127.0.0.1:{port}", WEIGHT_CHANNEL,
                               window=8, registry=reg)
        t0 = time.monotonic()
        sender.send_bytes(blob, sync=True, timeout=60)
        landed = recv.recv_bytes(timeout=60)
        meta, got = unpack_weights(landed)     # digest-verified landing
        warm_s = time.monotonic() - t0
        sender.close()
        assert tree_digest(got) == meta["digest"]
    finally:
        hub.stop()
    cold_s = load_s + trace_s                  # injected cold-start floors

    # -- 2. rolling upgrade: one seed + fan-out vs N serial loads ------------
    fleet = SimFleet(n_replicas, itl_s=0.002, slots=4,
                     weights_version="v-old", registry=MetricsRegistry())
    try:
        fleet.start()
        warmer = SimWarmer(fleet, "v-new", ship_s=ship_s, load_s=load_s)
        provider = SimProvider(fleet, weights_version=None)
        ctrl = FleetController(fleet.router, provider,
                               registry=MetricsRegistry(), warmer=warmer)
        new_addrs = [fleet.spawn(weights_version=None)
                     for _ in range(n_replicas)]
        t0 = time.monotonic()
        results = ctrl.rolling_upgrade(new_addrs)
        upgrade_wall = time.monotonic() - t0
        assert all(r.get("drained") for r in results.values()), results
        warm = ctrl.last_warm
        assert warm is not None and not warm["failed"], warm
        # O(log N) fan-out: 1 fallback wave mints the seed, then the
        # seeder pool doubles every ship wave
        assert warm["waves"] == 1 + math.ceil(math.log2(n_replicas)), warm
        assert warmer.loads == 1, warmer.loads
    finally:
        fleet.stop()
    serial_wall = n_replicas * load_s          # old path: N storage loads

    return {
        "serving_scaleup_to_first_token_s": round(warm_s, 4),
        "serving_scaleup_storage_load_s": round(cold_s, 4),
        # warm replica ready-to-serve speedup over cold start (pinned
        # >= 2 tier-1)
        "serving_scaleup_warm_vs_cold": round(cold_s / warm_s, 2),
        "serving_weight_ship_bytes": len(blob),
        "serving_upgrade_wall_s": round(upgrade_wall, 4),
        # one-seed + O(log N) fan-out vs N serial storage loads
        # (pinned > 1 tier-1)
        "serving_upgrade_wall_vs_serial_loads": round(
            serial_wall / upgrade_wall, 2),
        "serving_warm_waves": warm["waves"],
        "serving_warm_storage_loads": warmer.loads,
    }


def _prefix_arm(slots: int = 2, n_req: int = 8, prefix_len: int = 40,
                suffix_len: int = 8, budget: int = 4, chunk: int = 2,
                prefill_s_per_token: float = 0.002,
                fetch_floor_s: float = 0.01,
                one_way_s: float = 0.0) -> dict:
    """Prefix-aware routing + shared prefix tier vs prefix-blind
    placement, at ``n_req``x reuse of one shared prefix: time-to-first-
    token and prefill compute (forward tokens — the FLOPs proxy) across
    a 2-replica fleet behind the router, with token-identical output
    asserted between the two placements.

    Deterministic: a tiny CPU model plus injected floors — a prefill
    floor of ``prefill_s_per_token`` per token RUN THROUGH A FORWARD
    (so a prefix-hit admission's floor is O(suffix) while a blind
    admission's is O(prefix+suffix), exactly the compute shape on
    hardware) and a fixed per-sync fetch floor; a warm-up round
    compiles every program before anything is measured. The AWARE arm
    is the full tentpole path: the prefix is registered with the
    router, computed ONCE on replica A (``install``), and replica B
    warms in ONE template ship (``publish`` — zero prefix forwards on
    B, asserted); every session then admits only its suffix. The BLIND
    arm runs the same fleet with no prefix anywhere — every admission
    pays the full prefill floor. ``one_way_s`` (the @slow variant)
    routes the client through a LatencyProxy — the TTFT contrast is
    produced by admission compute, so a WAN hop shifts both arms
    equally."""
    import threading

    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.client import StreamingClient
    from tony_tpu.serving.netem import LatencyProxy
    from tony_tpu.serving.router import ServingRouter
    from tony_tpu.serving.server import ServingServer

    cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32, remat=False)
    params = T.init_params(jax.random.PRNGKey(0), cfg)

    class FloorBatcher(ContinuousBatcher):
        """Prefill floor proportional to tokens actually run through a
        forward (the host-side accounting the engine also folds into
        the metrics plane), plus a fixed per-sync fetch floor."""

        def _admit_prompts(self, pairs, prompts):
            before = self.prefill_forward_tokens
            super()._admit_prompts(pairs, prompts)
            time.sleep(prefill_s_per_token
                       * (self.prefill_forward_tokens - before))

        def _fetch(self, handle):
            if fetch_floor_s > 0:
                time.sleep(fetch_floor_s)
            return super()._fetch(handle)

    rs = np.random.RandomState(23)
    prefix = [int(t) for t in rs.randint(0, cfg.vocab_size,
                                         size=prefix_len)]
    prompts = [prefix + [int(t) for t in rs.randint(
        0, cfg.vocab_size, size=suffix_len)] for _ in range(n_req)]
    max_len = prefix_len + suffix_len + budget

    def run(aware: bool):
        regr = M.MetricsRegistry()
        batchers = [FloorBatcher(params, cfg, batch=slots,
                                 max_len=max_len, chunk=chunk)
                    for _ in range(2)]
        servers = [ServingServer(b, registry=M.MetricsRegistry())
                   for b in batchers]
        router = None
        proxy = None
        c = None
        try:
            addrs = [f"127.0.0.1:{s.start()}" for s in servers]
            pid = None
            if aware:
                # the tentpole path: compute ONCE on A, warm B in one
                # template ship, register with the router
                pid = servers[0].install_prefix(prefix, prefix_id="sys")
                ship_bytes = servers[0].publish_prefix(
                    pid, f"127.0.0.1:{servers[1].prefix_port}")
                deadline = time.time() + 10
                while (pid not in batchers[1].resident_prefixes()
                       and time.time() < deadline):
                    time.sleep(0.02)
                assert pid in batchers[1].resident_prefixes(), \
                    "template ship did not land"
            else:
                ship_bytes = 0
            router = ServingRouter(addrs, registry=regr,
                                   health_interval_s=0.1)
            if aware:
                router.register_prefix(prefix, prefix_id=pid)
            port = router.start()
            if one_way_s > 0:
                proxy = LatencyProxy("127.0.0.1", port, one_way_s)
                port = proxy.start()
            c = StreamingClient("127.0.0.1", port)
            # warm round: compile every admission/step program on both
            # replicas before anything is measured
            for p in prompts:
                c.result(c.submit(p, budget), timeout=120)
            fwd0 = sum(b.prefill_forward_tokens for b in batchers)
            outs: list = [None] * n_req
            ttfts: list = [0.0] * n_req

            def drain(i, rid, t_submit):
                toks, first = [], None
                for delta in c.deltas(rid, timeout=120):
                    if first is None:
                        first = time.perf_counter()
                    toks.extend(delta)
                outs[i] = toks
                ttfts[i] = (first or time.perf_counter()) - t_submit

            threads = []
            for i, p in enumerate(prompts):
                rid = c.submit(p, budget)
                th = threading.Thread(target=drain,
                                      args=(i, rid, time.perf_counter()))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            fwd = sum(b.prefill_forward_tokens for b in batchers) - fwd0
            hits = regr.counter("tony_router_prefix_hits_total").value
            misses = regr.counter(
                "tony_router_prefix_misses_total").value
            if aware:
                # B warmed by the SHIP: every B admission was a hit, so
                # its lifetime forward tokens are suffixes only — zero
                # prefill forwards for the shipped prefix
                assert batchers[1].prefill_forward_tokens == \
                    suffix_len * batchers[1].prefix_admits, \
                    "cold replica ran a prefix forward despite the ship"
            return (outs, sum(ttfts) / len(ttfts), fwd, hits, misses,
                    ship_bytes)
        finally:
            if c is not None:
                c.close()
            if proxy is not None:
                proxy.stop()
            if router is not None:
                router.stop()
            for s in servers:
                s.stop()

    outs_blind, ttft_blind, fwd_blind, _, _, _ = run(aware=False)
    outs_aware, ttft_aware, fwd_aware, hits, misses, ship_bytes = run(
        aware=True)
    assert outs_blind == outs_aware, (
        "prefix-aware serving diverged from prefix-blind — template "
        "corruption")
    return {
        "serving_prefix_reuse": n_req,
        "serving_prefix_prefill_s_per_token": prefill_s_per_token,
        "serving_prefix_ttft_blind_s": round(ttft_blind, 4),
        "serving_prefix_ttft_aware_s": round(ttft_aware, 4),
        # the tentpole ratio: at >=8x reuse, placing sessions where the
        # prefix KV lives cuts TTFT by the prefill share the suffix
        # no longer pays (>= 2 tier-1-pinned)
        "serving_prefix_ttft_vs_blind": round(
            ttft_blind / max(ttft_aware, 1e-9), 2),
        # the FLOPs story: forward tokens in the measured round
        "serving_prefix_forward_tokens_blind": int(fwd_blind),
        "serving_prefix_forward_tokens_aware": int(fwd_aware),
        "serving_prefix_forward_vs_blind": round(
            fwd_blind / max(fwd_aware, 1), 2),
        # every prefix session landed on a resident replica
        "serving_prefix_hit_rate": round(
            hits / max(hits + misses, 1), 3),
        "serving_prefix_ship_bytes": int(ship_bytes),
    }


def _ring_flash_arm(b=4, s=8192, h=8, d=64, iters=8):
    from tony_tpu.ops.attention import (flash_attention,
                                        flash_attention_with_lse)

    half = s // 2
    q = jax.random.normal(jax.random.PRNGKey(11), (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(12), (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(13), (b, s, h, d), jnp.bfloat16)

    def two_chunk(q, k, v):
        # q0 sees only the diagonal chunk; q1 sees one past hop (full)
        # merged with its diagonal chunk — the 2-device causal ring,
        # laid out sequentially on one chip
        q0, q1 = q[:, :half], q[:, half:]
        k0, k1 = k[:, :half], k[:, half:]
        v0, v1 = v[:, :half], v[:, half:]
        o0, _ = flash_attention_with_lse(q0, k0, v0, causal=True)
        o10, lse10 = flash_attention_with_lse(q1, k0, v0, causal=False)
        o11, lse11 = flash_attention_with_lse(q1, k1, v1, causal=True)
        lse1 = jnp.logaddexp(lse10, lse11)
        to_bshd = lambda w: w.transpose(0, 2, 1)[..., None]
        o1 = (o10.astype(jnp.float32) * to_bshd(jnp.exp(lse10 - lse1))
              + o11.astype(jnp.float32) * to_bshd(jnp.exp(lse11 - lse1)))
        return jnp.concatenate([o0.astype(jnp.float32), o1], axis=1)

    mono = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    ring = jax.jit(two_chunk)
    err = float(jnp.max(jnp.abs(ring(q, k, v)
                                - mono(q, k, v).astype(jnp.float32))))
    grad = jax.jit(jax.grad(lambda q, k, v: two_chunk(q, k, v).sum(),
                            argnums=(0, 1, 2)))
    g = grad(q, k, v)
    float(g[0][0, 0, 0, 0].astype(jnp.float32))         # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        g = grad(q, k, v)
    float(g[0][0, 0, 0, 0].astype(jnp.float32))
    tps = b * s * iters / (time.perf_counter() - t0)
    return {"ringflash_tokens_per_s": round(tps, 1),
            "ringflash_vs_mono_maxerr": round(err, 5)}


def _serving_decode_arm(cfg, batch: int = 8, prompt_len: int = 128,
                        steps: int = 256):
    """Decode throughput vs padded cache size at FIXED generated length.

    A serving cache is sized for the longest request (2k-32k), while most
    requests finish far shorter; the dense cached-attention einsum pays
    for every padded row anyway. This arm prefills+scans ``steps`` greedy
    tokens into caches padded to 2048 and 8192 positions (live length
    <= 384 throughout) and reports tokens/s at each — ~flat under the
    block-wise length-aware path — plus a dense-forced 2048 contrast
    (the pre-round-5 behavior, linear in max_len)."""
    from tony_tpu.models import decode as D
    from tony_tpu.models import transformer as T

    params = T.init_params(jax.random.PRNGKey(0), cfg)

    def make_fns(max_len, run_cfg):
        # fresh closures per variant: the blockwise/dense dispatch happens
        # at trace time off D._BLOCKWISE_MIN_LEN, so variants must not
        # share a jit cache entry
        @jax.jit
        def do_prefill(p, toks):
            return D.prefill(p, toks, run_cfg, max_len)

        @functools.partial(jax.jit, static_argnames=("n",))
        def scan_decode(p, logits, cache, n):
            def step(carry, _):
                lg, c = carry
                token = jnp.argmax(lg, axis=-1)
                lg, c = D.decode_step(p, token, c, c["length"], run_cfg)
                return (lg, c), token

            (_, _), gen = jax.lax.scan(step, (logits, cache), None,
                                       length=n)
            return gen

        return do_prefill, scan_decode

    def time_one(max_len, force_dense=False, b=batch, run_cfg=cfg,
                 p_len=prompt_len, p=params):
        prompt = jax.random.randint(jax.random.PRNGKey(17),
                                    (b, p_len), 0, cfg.vocab_size)
        saved = D._BLOCKWISE_MIN_LEN
        if force_dense:
            D._BLOCKWISE_MIN_LEN = 1 << 30
        try:
            do_prefill, scan_decode = make_fns(max_len, run_cfg)
            # prefill (incl. the O(max_len) cache zero-init) runs OUTSIDE
            # the timed region — the metric is decode-step cost vs padded
            # max_len, and the fixed prefill would pull the ratio toward 1
            # while the init's max_len-scaled writes pull it away
            logits, cache = do_prefill(p, prompt)
            gen = scan_decode(p, logits, cache, steps)
            int(gen[0, 0])                       # compile + warm
            reps = []
            for _ in range(3):
                t0 = time.perf_counter()
                gen = scan_decode(p, logits, cache, steps)
                int(gen[0, 0])
                reps.append(time.perf_counter() - t0)
            return b * steps / sorted(reps)[1]
        finally:
            D._BLOCKWISE_MIN_LEN = saved

    tps2k = time_one(2048)
    tps8k = time_one(8192)
    tps2k_dense = time_one(2048, force_dense=True)
    # serving-batch amortization: the b8 step is per-op-overhead-bound
    # (~25 us/layer of fori_loop glue vs ~5 us of cache traffic —
    # docs/performance.md flash-decode negative result), so a wider
    # serving batch amortizes the fixed cost across 4x the rows; the
    # per-slot ratio (wide/base throughput over the batch ratio) is the
    # overhead share a batching queue can reclaim
    wide = 4 * batch
    tps2k_wide = time_one(2048, b=wide)
    # int8 KV cache: HBM footprint and cache read traffic halve vs bf16
    # (a serving host fits ~2x the slots or 2x max_len); throughput at
    # the SAME shape should hold near parity — the b8 step is per-op-
    # overhead-bound, not bandwidth-bound (docs/performance.md) — so the
    # ratio below is a regression guard for the capacity win, not a
    # speed claim
    qcfg = cfg.scaled(kv_cache_dtype="int8")
    tps8k_quant = time_one(8192, run_cfg=qcfg)
    tps2k_wide_quant = time_one(2048, b=wide, run_cfg=qcfg)
    # sliding-window decode at DEEP history (7k-token prompt): full
    # attention walks every live cache block per token; a window-1024
    # model walks ~4 blocks regardless of history — per-token serving
    # cost O(window), the decode-side claim of attn_window.
    deep = 7168
    tps_deep_full = time_one(8192, p_len=deep)
    tps_deep_win = time_one(8192, p_len=deep,
                            run_cfg=cfg.scaled(attn_window=1024))
    # rolling ring-buffer cache (kv_cache_capacity): same windowed math
    # over a capacity-row ring instead of the max_len buffer — 8x less
    # cache memory at this shape, measured speed parity; the length
    # ceiling disappears (requests may run past max_len)
    tps_deep_ring = time_one(8192, p_len=deep,
                             run_cfg=cfg.scaled(attn_window=1024,
                                                kv_cache_capacity=1024))
    # weight-only int8 (models/quantize.py): halves the matmul weights'
    # HBM read (the parameter-bound share of small-batch decode); the
    # all-int8 arm composes it with the int8 KV cache at the wide batch
    from tony_tpu.models.quantize import quantize_weights_int8
    wq = quantize_weights_int8(params)
    tps2k_wq = time_one(2048, p=wq)
    tps2k_wide_all8 = time_one(2048, b=wide, run_cfg=qcfg, p=wq)

    # quantized PREFILL: prefill over a long prompt is compute-bound
    # (the opposite regime from decode), so _weinsum's prefill-shaped
    # path converts the int8 weights to bf16 once per call and runs the
    # dots at bf16 MXU throughput — both-operands-f32 (the decode trade)
    # measured far below bf16 there. The ratio below pins the win; a
    # regression back toward all-f32 prefill shows up directly.
    def time_prefill(p, b_p=8, s_p=1024):
        toks = jax.random.randint(jax.random.PRNGKey(21), (b_p, s_p), 0,
                                  cfg.vocab_size)
        fn = jax.jit(lambda pp, tk: D.prefill(pp, tk, cfg, 2048)[0])
        float(fn(p, toks)[0, 0])                 # compile + warm
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(p, toks)[0, 0])
            reps.append(time.perf_counter() - t0)
        return b_p * s_p / sorted(reps)[1]

    tps_prefill = time_prefill(params)
    tps_prefill_wq = time_prefill(wq)
    return {
        "decode_maxlen2k_tokens_per_s": round(tps2k, 1),
        "decode_maxlen8k_tokens_per_s": round(tps8k, 1),
        "decode_maxlen2k_dense_tokens_per_s": round(tps2k_dense, 1),
        # ~1.0 = cost flat in padded max_len (the done-criterion)
        "decode_maxlen_8k_vs_2k": round(tps8k / tps2k, 3),
        f"decode_maxlen2k_b{wide}_tokens_per_s": round(tps2k_wide, 1),
        f"decode_b{wide}_vs_b{batch}_per_slot": round(
            tps2k_wide / tps2k / (wide / batch), 2),
        "decode_quant8_maxlen8k_tokens_per_s": round(tps8k_quant, 1),
        "decode_quant8_vs_bf16_8k": round(tps8k_quant / tps8k, 2),
        f"decode_quant8_maxlen2k_b{wide}_tokens_per_s": round(
            tps2k_wide_quant, 1),
        f"decode_quant8_vs_bf16_2k_b{wide}": round(
            tps2k_wide_quant / tps2k_wide, 2),
        "decode_deep7k_tokens_per_s": round(tps_deep_full, 1),
        "decode_deep7k_win1k_tokens_per_s": round(tps_deep_win, 1),
        "decode_win1k_vs_full_deep7k": round(
            tps_deep_win / tps_deep_full, 2),
        "decode_ring1k_deep7k_tokens_per_s": round(tps_deep_ring, 1),
        "decode_ring_vs_linear_win_deep7k": round(
            tps_deep_ring / tps_deep_win, 2),
        "decode_wq8_maxlen2k_tokens_per_s": round(tps2k_wq, 1),
        "decode_wq8_vs_bf16_2k": round(tps2k_wq / tps2k, 2),
        f"decode_all_int8_b{wide}_tokens_per_s": round(
            tps2k_wide_all8, 1),
        f"decode_all_int8_vs_bf16_b{wide}": round(
            tps2k_wide_all8 / tps2k_wide, 2),
        "prefill_b8_1k_tokens_per_s": round(tps_prefill, 1),
        "prefill_wq8_b8_1k_tokens_per_s": round(tps_prefill_wq, 1),
        # near 1.0 = quantized serving no longer pays an f32-prefill
        # latency tax (the pre-change all-f32 path sat well below it)
        "prefill_wq8_vs_bf16": round(tps_prefill_wq / tps_prefill, 2),
    }


def _continuous_batching_arm(cfg, slots: int = 8, prompt_len: int = 64):
    """Continuous batching vs static batches at mixed generation budgets.

    24 requests, budgets cycling 32..256 (mean 144) through 8 slots. The
    static baseline runs batches of 8 to each batch's LONGEST budget —
    what plain generate() serving does; finished rows ride dead until
    the stragglers finish. Reported both ways: wall-clock useful-token
    throughput (includes the tunnel's per-chunk sync cost the continuous
    loop pays) and step utilization = useful tokens / (decode steps x
    slots), the transport-independent number."""
    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.decode import generate
    from tony_tpu.models.serve import ContinuousBatcher

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(5)
    # one 256 per batch-of-8 keeps the static arm at ONE compile
    base = [256, 32, 64, 96, 128, 160, 192, 224]
    budgets = sum(([*rs.permutation(base)] for _ in range(3)), [])
    budgets = [int(b) for b in budgets]
    prompts = [list(rs.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in budgets]
    useful = sum(budgets)
    max_len = prompt_len + 256

    # pipelined (default) loop: chunk N+1 dispatched before chunk N's
    # fetch, so the tunnel round trip overlaps device compute
    batcher = ContinuousBatcher(params, cfg, batch=slots, max_len=max_len,
                                chunk=16)
    batcher.serve(prompts[:slots], [16] * slots)      # compile + warm
    t0 = time.perf_counter()
    batcher.serve(prompts, budgets)
    t_cb = time.perf_counter() - t0
    cb_steps = batcher.steps_executed
    cb_phases = batcher.phase_times

    # sequential contrast (the pre-pipelining loop): every fetch
    # serializes the round trip with compute — the overlap win is the
    # ratio between these two on identical workload and device programs
    seq = ContinuousBatcher(params, cfg, batch=slots, max_len=max_len,
                            chunk=16, pipeline=False)
    seq.serve(prompts[:slots], [16] * slots)          # compile + warm
    t0 = time.perf_counter()
    seq.serve(prompts, budgets)
    t_cb_seq = time.perf_counter() - t0

    gen = functools.partial(generate, cfg=cfg, max_new_tokens=256,
                            temperature=0.0)
    warm_prompt = jnp.asarray(prompts[:slots], jnp.int32)
    out = gen(params, warm_prompt, rng=jax.random.PRNGKey(0))
    int(out.tokens[0, 0])                             # compile + warm
    static_steps = 0
    t0 = time.perf_counter()
    for i in range(0, len(prompts), slots):
        batch_prompts = jnp.asarray(prompts[i:i + slots], jnp.int32)
        out = gen(params, batch_prompts, rng=jax.random.PRNGKey(0))
        int(out.tokens[0, 0])
        static_steps += max(budgets[i:i + slots])
    t_static = time.perf_counter() - t0

    return {
        # step utilization is the transport-independent serving metric
        # (useful tokens per slot-step); the wall ratio on THIS rig is
        # dominated by ~70-100 ms tunnel round trips per chunk/admit
        # sync — the pipelined loop overlaps each sync with the NEXT
        # chunk's device compute, which a co-located serving host also
        # benefits from (fetch + bookkeeping hidden behind compute).
        # On a budget-only workload the pipelined loop runs the same
        # chunk count as the sequential loop (admission events process
        # synchronously — serve.py defer_issue), so the util numbers
        # are directly comparable across rounds.
        "serving_cb_step_util": round(useful / (cb_steps * slots), 3),
        "serving_static_step_util": round(
            useful / (static_steps * slots), 3),
        "serving_cb_tokens_per_s_tunneled": round(useful / t_cb, 1),
        "serving_cb_sequential_tokens_per_s_tunneled": round(
            useful / t_cb_seq, 1),
        # the overlap win, same programs and workload both sides
        "serving_cb_pipelined_vs_sequential": round(t_cb_seq / t_cb, 2),
        "serving_static_tokens_per_s": round(useful / t_static, 1),
        "serving_cb_vs_static_wall_tunneled": round(t_static / t_cb, 2),
        # per-sync host phases (pipelined run): fetch is the blocking
        # transport+compute wait the overlap hides; dispatch is pure
        # host-side enqueue cost
        "serving_cb_fetch_ms_per_sync": round(
            1e3 * cb_phases.total("fetch")
            / max(1, cb_phases.count("fetch")), 1),
        "serving_cb_dispatch_ms_per_sync": round(
            1e3 * cb_phases.total("dispatch")
            / max(1, cb_phases.count("dispatch")), 1),
    }


def _admission_arm(cfg, slots: int = 8, n_req: int = 32,
                   budget: int = 8):
    """Admission cost: bucketed+batched vs per-length admission.

    A churn-heavy workload (short budgets → admission-dominated): 32
    requests over 12 DISTINCT prompt lengths (33..121) spanning two
    power-of-two buckets (64, 128). Wall time per admission INCLUDES
    each path's compiles — the per-length path recompiles for every new
    prompt length, which IS its cost on real traffic (a serving host
    sees arbitrary lengths forever), while the bucketed path compiles
    once per bucket and pads. (12 distinct lengths, not 30+, keeps the
    legacy arm's compile bill bounded on a cold cache while still
    making the retrace cost unmistakable.) The admit phase is taken
    from the batcher's own PhaseTimes, so the number excludes
    decode/fetch time on both sides."""
    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(9)
    distinct = [33 + 8 * i for i in range(12)]            # 33..121
    prompts = [list(rs.randint(0, cfg.vocab_size, size=int(n)))
               for n in rs.choice(distinct, size=n_req)]
    max_len = 128 + 2 * budget

    def admit_ms_per_req(bucketed):
        b = ContinuousBatcher(params, cfg, batch=slots, max_len=max_len,
                              chunk=budget, bucketed_admission=bucketed)
        b.serve(prompts, budget)
        return 1e3 * b.phase_times.total("admit") / n_req

    ms_bucketed = admit_ms_per_req(True)
    ms_perlen = admit_ms_per_req(False)
    return {
        "serving_admit_ms_per_req_bucketed": round(ms_bucketed, 2),
        "serving_admit_ms_per_req_perlength": round(ms_perlen, 2),
        "serving_admission_speedup": round(ms_perlen / ms_bucketed, 2),
    }


def _metrics_overhead_arm(cfg, slots: int = 8, prompt_len: int = 64,
                          budget: int = 128):
    """Metrics-registry overhead on the serve hot loop.

    The continuous batcher observes a handful of counters/gauges per host
    SYNC (not per token) and folds PhaseTimes once per serve() call —
    this arm verifies that stays free. Two measurements: (a) the same
    mixed workload served with the default registry vs a NullRegistry
    (whole-loop A/B — the ratio should be ~1.0, i.e. within the rig's
    run-to-run noise); (b) a direct microbench of one observation through
    the registry's get-or-create fast path, asserted to be < 1% of the
    measured per-sync chunk wall (the issue's hard bound — registry cost
    must never show up in serving latency)."""
    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.runtime import metrics as M

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(3)
    prompts = [list(rs.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in range(2 * slots)]

    def timed_serve():
        b = ContinuousBatcher(params, cfg, batch=slots,
                              max_len=prompt_len + budget, chunk=16)
        b.serve(prompts[:slots], [16] * slots)       # compile + warm
        t0 = time.perf_counter()
        b.serve(prompts, budget)
        return time.perf_counter() - t0, b

    saved = M.set_default(M.MetricsRegistry())
    try:
        t_on, b_on = timed_serve()
        syncs = max(1, b_on.phase_times.count("fetch"))
        M.set_default(M.NullRegistry())
        t_off, _ = timed_serve()
    finally:
        M.set_default(saved)

    # one observation through the exact serve call shape (lookup + inc)
    reg = M.MetricsRegistry()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        reg.counter("bench_obs_total").inc()
    per_obs_s = (time.perf_counter() - t0) / n
    # the serve loop makes <~8 registry touches per sync (admit/retire/
    # token/queue-depth counters) plus one TTFT-or-ITL histogram observe
    # per DELTA (<= slots per sync) plus an O(#phases) fold per CALL
    obs_per_sync = 8 + 2 * slots
    frac = per_obs_s * obs_per_sync / (t_on / syncs)
    assert frac < 0.01, (
        f"registry observations are {frac:.2%} of per-sync chunk wall — "
        f"the metrics plane is no longer free on the serve loop")
    return {
        "serving_metrics_obs_ns": round(per_obs_s * 1e9, 1),
        "serving_metrics_obs_frac_of_chunk": round(frac, 6),
        # ~1.0 = instrumented serve within noise of uninstrumented
        "serving_metrics_instrumented_vs_null": round(t_on / t_off, 3),
    }


def _trace_overhead_arm(cfg, slots: int = 8, prompt_len: int = 64,
                        budget: int = 128):
    """Tracing-plane overhead on the serve hot loop + export validity.

    The engine opens ~3 spans per request (engine.request / .queued /
    .first_token — the TTFT decomposition) when sampling is on. Two
    measurements, the metrics arm's discipline: (a) the same workload
    served with sampling ON (rate 1.0) vs tracing OFF — the whole-loop
    A/B should sit within run noise; (b) a direct microbench of one
    start+end span through the tracer, asserted < 1 % of per-sync chunk
    wall at the engine's spans-per-sync worst case. The bench job's own
    exported trace must round-trip as schema-valid Chrome trace JSON
    (every event a complete ``X`` with name/ts/dur/pid/tid, or an ``M``
    metadata record)."""
    import numpy as np

    from tony_tpu.models import transformer as T
    from tony_tpu.models.serve import ContinuousBatcher
    from tony_tpu.runtime import metrics as M
    from tony_tpu.runtime import tracing

    params = T.init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(7)
    prompts = [list(rs.randint(0, cfg.vocab_size, size=prompt_len))
               for _ in range(2 * slots)]

    def timed_serve():
        b = ContinuousBatcher(params, cfg, batch=slots,
                              max_len=prompt_len + budget, chunk=16)
        b.serve(prompts[:slots], [16] * slots)       # compile + warm
        t0 = time.perf_counter()
        b.serve(prompts, budget)
        return time.perf_counter() - t0, b

    saved_reg = M.set_default(M.MetricsRegistry())
    saved_tr = tracing.set_tracer(
        tracing.Tracer(proc="bench:0", sample_rate=1.0, ring_size=8192))
    try:
        t_on, b_on = timed_serve()
        syncs = max(1, b_on.phase_times.count("fetch"))
        spans = tracing.get_tracer().recent()
        tracing.set_tracer(tracing.Tracer(proc="bench:0", enabled=False))
        t_off, _ = timed_serve()
    finally:
        tracing.set_tracer(saved_tr)
        M.set_default(saved_reg)

    # schema-valid Chrome trace from the sampled run's spans: JSON
    # round-trip + the invariants a viewer depends on
    assert spans, "sampled serve recorded no spans"
    chrome = json.loads(json.dumps(tracing.to_chrome(spans)))
    assert isinstance(chrome["traceEvents"], list) and chrome["traceEvents"]
    for e in chrome["traceEvents"]:
        assert e["ph"] in ("X", "M"), e
        if e["ph"] == "X":
            assert isinstance(e["name"], str) and e["name"]
            for key in ("ts", "dur"):
                assert isinstance(e[key], (int, float)) and e[key] >= 0, e
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    names = {e["name"] for e in chrome["traceEvents"] if e["ph"] == "X"}
    assert {"engine.request", "engine.queued",
            "engine.first_token"} <= names, names

    # one start+end through the tracer, the exact engine call shape
    tr = tracing.Tracer(proc="bench:0", sample_rate=1.0, ring_size=512)
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        tr.start_span("bench.span").end()
    per_span_s = (time.perf_counter() - t0) / n
    # worst case per host sync: every slot retires and readmits — one
    # request span ends + queued/first spans cycle (~3 span ops/slot)
    spans_per_sync = 3 * slots
    frac = per_span_s * spans_per_sync / (t_on / syncs)
    assert frac < 0.01, (
        f"span records are {frac:.2%} of per-sync chunk wall — the "
        f"tracing plane is no longer free on the serve loop")
    return {
        "serving_trace_span_ns": round(per_span_s * 1e9, 1),
        "serving_trace_span_frac_of_chunk": round(frac, 6),
        # ~1.0 = sampled-on serve within noise of tracing-off
        "serving_trace_sampled_vs_off": round(t_on / t_off, 3),
        "serving_trace_spans_recorded": len(spans),
    }


def _speculative_arm(new: int = 256, k: int = 10):
    """Batch-1 greedy vs device-loop speculative decoding, same target.

    Speculation only pays when the draft predicts the target, so the arm
    first trains target (base preset) and draft (1 layer, d128 — ~4% of
    the target's step cost) on a deterministic affine token chain both
    learn quickly; the measured ratio is then a REAL acceptance-driven
    win, not a fixture. Token match vs greedy is reported (bf16 chunk-vs-
    step near-ties can flip occasional tokens, as documented in
    models/decode.py)."""
    from tony_tpu.models import transformer as T
    from tony_tpu.models.decode import (generate,
                                        speculative_generate_device)
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)

    cfg_t = T.PRESETS["base"].scaled(remat=False)
    cfg_d = T.PRESETS["base"].scaled(n_layers=1, d_model=128, n_heads=2,
                                     d_ff=512, remat=False)

    def make_data(rng, batch, seq):
        x0 = jax.random.randint(rng, (batch, 1), 0, 4099)

        def step(carry, _):
            nxt = (13 * carry + 7) % 4099
            return nxt, nxt

        _, xs = jax.lax.scan(step, x0, None, length=seq)
        toks = jnp.concatenate([x0, xs.squeeze(-1).T], axis=1)
        return {"inputs": toks[:, :seq], "targets": toks[:, 1:]}

    def train(cfg, steps, seed, snapshots=()):
        """Returns final params, plus params snapshotted at the requested
        step counts — one run covers a whole draft-quality sweep (the
        weaker drafts are exact prefixes of the deterministic stream)."""
        params = T.init_params(jax.random.PRNGKey(seed), cfg)
        opt = default_optimizer(lr=1e-3)
        state = init_state(params, opt)
        step = make_train_step(lambda p, b: T.lm_loss(p, b, cfg), opt)
        snaps = {}
        for i in range(steps):
            if i in snapshots:
                # deep-copy: the train step DONATES its state, so a bare
                # reference would be a deleted buffer one step later
                snaps[i] = jax.tree.map(jnp.copy, state["params"])
            state, _ = step(state,
                            make_data(jax.random.PRNGKey(1000 + i), 16, 256))
        return (state["params"], snaps) if snapshots else state["params"]

    p_t = train(cfg_t, 120, 0)
    # draft quality sweep: 400 steps ≈ near-perfect acceptance on this
    # task; 100/25 are the mediocre/weak drafts the acceptance sweep
    # below measures (the regime where min-commit decayed)
    p_d, snaps = train(cfg_d, 400, 1, snapshots=(25, 100))
    p_d_weak, p_d_mid = snaps[25], snaps[100]
    prompt = make_data(jax.random.PRNGKey(7), 1, 65)["inputs"][:, :64]
    greedy = functools.partial(generate, cfg=cfg_t, max_new_tokens=new,
                               temperature=0.0)
    spec = jax.jit(functools.partial(
        speculative_generate_device, cfg=cfg_t, draft_cfg=cfg_d,
        max_new_tokens=new, num_speculative=k))
    out_g = greedy(p_t, prompt, rng=jax.random.PRNGKey(0))
    out_s = spec(p_t, p_d, prompt)
    match = float((out_g.tokens[0, -new:] == out_s[0, -new:]).mean())
    ts_g, ts_s = [], []
    for rep in range(4):                    # interleaved, median wins
        t0 = time.perf_counter()
        for i in range(3):
            out_g = greedy(p_t, prompt, rng=jax.random.PRNGKey(i))
        int(out_g.tokens[0, -1])
        ts_g.append((time.perf_counter() - t0) / 3)
        t0 = time.perf_counter()
        for _ in range(3):
            out_s = spec(p_t, p_d, prompt)
        int(out_s[0, -1])
        ts_s.append((time.perf_counter() - t0) / 3)
    tg = sorted(ts_g)[len(ts_g) // 2]
    tsp = sorted(ts_s)[len(ts_s) // 2]
    out = {"spec_decode_tokens_per_s": round(new / tsp, 1),
           "greedy_b1_tokens_per_s": round(new / tg, 1),
           "spec_vs_greedy": round(tg / tsp, 2),
           "spec_token_match": round(match, 3)}
    # batch>1 acceptance sweep (per-row frontiers vs the min-commit
    # baseline): per-row commits let each row keep its own acceptance,
    # so the b8 ratio should hold up as the draft weakens — min-commit
    # decays with the batch MINIMUM. tokens/round recorded for both.
    # DISTINCT prompts per row: tiling one prompt would sync the rows'
    # acceptances and flatter both policies.
    b8 = make_data(jax.random.PRNGKey(8), 8, 64)["inputs"]
    og = greedy(p_t, b8, rng=jax.random.PRNGKey(0)); int(og.tokens[0, -1])
    t0 = time.perf_counter()
    for i in range(3):
        og = greedy(p_t, b8, rng=jax.random.PRNGKey(i))
    int(og.tokens[0, -1])
    t_g8 = (time.perf_counter() - t0) / 3

    # ONE jitted fn per commit policy, hoisted out of the draft loop:
    # draft params are runtime args, so all three drafts share a compile
    spec_fns = {
        commit: jax.jit(functools.partial(
            speculative_generate_device, cfg=cfg_t, draft_cfg=cfg_d,
            max_new_tokens=new, num_speculative=k, commit=commit,
            return_rounds=True))
        for commit in ("per_row", "min", "window")
    }

    def time_spec_b8(draft_p, commit):
        fn = spec_fns[commit]
        o, rounds = fn(p_t, draft_p, b8)
        int(o[0, -1])                            # compile + warm
        t0 = time.perf_counter()
        for _ in range(3):
            o, rounds = fn(p_t, draft_p, b8)
        int(o[0, -1])
        return (time.perf_counter() - t0) / 3, int(rounds)

    for name, draft_p in (("", p_d), ("_d100", p_d_mid),
                          ("_d25", p_d_weak)):
        t_pr, r_pr = time_spec_b8(draft_p, "per_row")
        t_mc, r_mc = time_spec_b8(draft_p, "min")
        # bounded-window commit: per-row acceptance, scatter-free writes
        # (one contiguous window slice + MXU one-hot merge per layer)
        t_wd, r_wd = time_spec_b8(draft_p, "window")
        out[f"spec_b8_vs_greedy{name}"] = round(t_g8 / t_pr, 2)
        out[f"spec_b8_mincommit_vs_greedy{name}"] = round(t_g8 / t_mc, 2)
        out[f"spec_b8_window_vs_greedy{name}"] = round(t_g8 / t_wd, 2)
        out[f"spec_b8_tokens_per_round{name}"] = round(new / r_pr, 2)
        out[f"spec_b8_mincommit_tokens_per_round{name}"] = round(
            new / r_mc, 2)
        out[f"spec_b8_window_tokens_per_round{name}"] = round(
            new / r_wd, 2)
    # speculative SAMPLING (temperature > 0): same round machinery with
    # the min(1, p/q) accept test — committed stream distributed as
    # direct target sampling; the win rides the draft's acceptance just
    # like the greedy case. Temperature-only on this task: sampling
    # wanders OFF the deterministic affine chain, and on those
    # out-of-distribution contexts the toy draft's nucleus no longer
    # overlaps the target's — top_p=0.9 measured acceptance collapse
    # (1.17 tokens/round, 0.13x) where temperature-only holds 4.8
    # tokens/round (see docs/performance.md)
    gen_s = functools.partial(generate, cfg=cfg_t, max_new_tokens=new,
                              temperature=0.9)
    spec_s = jax.jit(functools.partial(
        speculative_generate_device, cfg=cfg_t, draft_cfg=cfg_d,
        max_new_tokens=new, num_speculative=k, temperature=0.9))
    og = gen_s(p_t, b8, rng=jax.random.PRNGKey(0)); int(og.tokens[0, -1])
    os_ = spec_s(p_t, p_d, b8, rng=jax.random.PRNGKey(0)); int(os_[0, -1])
    t0 = time.perf_counter()
    for i in range(3):
        og = gen_s(p_t, b8, rng=jax.random.PRNGKey(i))
    int(og.tokens[0, -1])
    t_gs = (time.perf_counter() - t0) / 3
    t0 = time.perf_counter()
    for i in range(3):
        os_ = spec_s(p_t, p_d, b8, rng=jax.random.PRNGKey(i))
    int(os_[0, -1])
    t_ss = (time.perf_counter() - t0) / 3
    out["spec_b8_sampled_vs_sampled"] = round(t_gs / t_ss, 2)

    out.update(_spec_serving_arm(cfg_t, cfg_d, p_t, p_d,
                                 make_data, new=new, k=k))
    return out


def _spec_serving_arm(cfg_t, cfg_d, p_t, p_d, make_data, new, k,
                      slots: int = 8, n_req: int = 16):
    """Continuous batching WITH speculative decoding vs greedy continuous
    batching, same workload and slot count, trained draft (the two
    serving features composed). Both loops pay the tunnel's per-sync
    round trip on this rig, so the ratio is transport-fair; a co-located
    host sees both numbers higher. rounds/tokens recorded for the
    speculative side (tokens-per-round = acceptance efficiency inside
    the serving loop)."""
    from tony_tpu.models.serve import (ContinuousBatcher,
                                       SpeculativeContinuousBatcher)

    prompts = [list(map(int, make_data(jax.random.PRNGKey(50 + i), 1, 65)
                        ["inputs"][0, :64])) for i in range(n_req)]
    useful = n_req * new
    max_len = 64 + new

    greedy_b = ContinuousBatcher(p_t, cfg_t, batch=slots, max_len=max_len,
                                 chunk=16)
    greedy_b.serve(prompts[:slots], [16] * slots)        # compile + warm
    t0 = time.perf_counter()
    greedy_b.serve(prompts, new)
    t_greedy = time.perf_counter() - t0

    spec_b = SpeculativeContinuousBatcher(
        p_t, cfg_t, p_d, cfg_d, batch=slots, max_len=max_len,
        num_speculative=k, chunk=2)
    spec_b.serve(prompts[:slots], [16] * slots)          # compile + warm
    t0 = time.perf_counter()
    spec_b.serve(prompts, new)
    t_spec = time.perf_counter() - t0

    return {
        "serving_spec_cb_tokens_per_s_tunneled": round(useful / t_spec, 1),
        "serving_greedy_cb_tokens_per_s_tunneled": round(
            useful / t_greedy, 1),
        "serving_spec_cb_vs_greedy_cb": round(t_greedy / t_spec, 2),
        "serving_spec_cb_tokens_per_round": round(
            useful / (slots * spec_b.rounds_executed), 2),
    }




def _pipeline_arm(num_microbatches: int = 8, one_way_s: float = 0.05,
                  fwd_floor_s: float = 0.015, bwd_floor_s: float = 0.03,
                  dim: int = 8, mb_rows: int = 4,
                  window: int = 10, lookahead: int = 5) -> dict:
    """Cross-slice 1F1B over DCN: overlapped vs serialized stage
    execution, deterministically.

    Two in-process stage "gangs" (threads) train one 2-stage model over
    REAL loopback tensor channels, each hub fronted by a LatencyProxy
    injecting ``one_way_s`` of one-way link latency (RT = 2x) — the
    netem technique of the streaming arm, modeling DCN links, not
    serialization. Device compute is a fixed per-microbatch floor
    injected AROUND the (tiny) jitted stage programs, so both runs
    execute the identical schedule on any rig:

    - **overlapped**: channel sends enqueue into the bounded window and
      return; transport of microbatch m±1 rides the wire while m
      computes. ``lookahead`` extra in-flight microbatches keep the
      steady-state loop (2 one-way hops + both stages' compute) full —
      Little's law: in-flight must exceed cycle/compute for throughput
      to be compute-bound, the MPMD-paper latency-tolerance knob. Wall
      ~ pipeline fill + M x max-stage-compute.
    - **serialized**: every send blocks until the peer's ack
      (``sync_transport=True``) — each activation/cotangent hop pays
      the full round trip serialized with compute, the cost model of
      stage execution WITHOUT a framework transport primitive.

    Loss and both stages' grads are asserted identical across the two
    runs (the schedule changes walls, never math). Emits
    ``pipeline_overlap_vs_serialized_wall`` (the tentpole ratio,
    tier-1-pinned >= 1.5) and ``pipeline_bubble_fraction`` (stage 0's
    1 - busy/wall under the overlapped run). The latency-realistic
    variant (tests/test_channels.py @slow) raises the delay and drops
    the floors."""
    import threading

    import numpy as np

    from tony_tpu.channels import open_local_pipeline
    from tony_tpu.parallel.pipeline import CrossSlicePipeline
    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.netem import LatencyProxy

    rs = np.random.RandomState(7)

    def stage_fn(p, x):
        return x + jnp.tanh(x @ p["w"] + p["b"])

    def loss_head(hp, out, tgt):
        return jnp.mean((out @ hp["wo"] - tgt) ** 2)

    p0 = {"w": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.3),
          "b": jnp.asarray(rs.randn(dim).astype(np.float32) * 0.1)}
    p1 = {"w": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.3),
          "b": jnp.asarray(rs.randn(dim).astype(np.float32) * 0.1)}
    head = {"wo": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.2)}
    m = num_microbatches
    xs = jnp.asarray(rs.randn(m, mb_rows, dim).astype(np.float32))
    tgts = jnp.asarray(rs.randn(m, mb_rows, dim).astype(np.float32))

    class FloorPipeline(CrossSlicePipeline):
        """Fixed per-microbatch device-compute floors: the deterministic
        stand-in for real stage compute (same technique as the
        streaming arm's FloorFetch)."""

        def _forward_compute(self, params, x):
            out = super()._forward_compute(params, x)
            jax.block_until_ready(out)
            time.sleep(fwd_floor_s)
            return out

        def _backward_compute(self, params, saved, cot):
            out = super()._backward_compute(params, saved, cot)
            jax.block_until_ready(out)
            time.sleep(bwd_floor_s)
            return out

        def _last_compute(self, params, head_params, saved, head_mb):
            out = super()._last_compute(params, head_params, saved,
                                        head_mb)
            jax.block_until_ready(out)
            time.sleep(fwd_floor_s + bwd_floor_s)
            return out

    def run_mode(sync: bool):
        reg = M.MetricsRegistry()
        proxies: list[LatencyProxy] = []

        def endpoint_map(stage_idx: int, port: int) -> str:
            proxy = LatencyProxy("127.0.0.1", port, one_way_s)
            proxies.append(proxy)
            return f"127.0.0.1:{proxy.start()}"

        links = open_local_pipeline(2, window=window, registry=reg,
                                    endpoint_map=endpoint_map)
        out: dict = {}
        try:
            pls = [
                FloorPipeline(stage_fn, links[0], registry=reg,
                              lookahead=lookahead, sync_transport=sync),
                FloorPipeline(stage_fn, links[1], loss_head=loss_head,
                              registry=reg, lookahead=lookahead,
                              sync_transport=sync),
            ]

            def run0():
                out[0] = pls[0].value_and_grad(
                    p0, num_microbatches=m, microbatches=xs)

            def run1():
                out[1] = pls[1].value_and_grad(
                    p1, num_microbatches=m, head_params=head,
                    head_batches=tgts)

            def one_round():
                ts = [threading.Thread(target=run0),
                      threading.Thread(target=run1)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=120)
                return time.perf_counter() - t0

            one_round()                     # compile + connect warmup
            wall = one_round()
            bubble = reg.gauge("tony_pipeline_bubble_fraction",
                               stage="0").value
            return wall, out, bubble, reg
        finally:
            for link in links:
                link.close()
            for proxy in proxies:
                proxy.stop()

    wall_ov, out_ov, bubble, reg_ov = run_mode(sync=False)
    wall_sr, out_sr, _, _ = run_mode(sync=True)

    def flat(res):
        loss = res[1][0]
        return ([np.asarray(loss)]
                + [np.asarray(v) for v in jax.tree.leaves(res[0][1])]
                + [np.asarray(v) for v in jax.tree.leaves(res[1][1])])

    for a, b in zip(flat(out_ov), flat(out_sr)):
        assert np.array_equal(a, b), \
            "overlapped vs serialized produced different math"
    # channel walls + queue depths must be VISIBLE on the metrics plane
    wire = reg_ov.to_wire()
    series = {name for name, _, _ in wire["h"]} \
        | {name for name, _, _ in wire["g"]}
    assert {"tony_channel_send_seconds", "tony_channel_recv_wait_seconds",
            "tony_channel_send_queue_depth",
            "tony_pipeline_step_seconds"} <= series, series
    return {
        "pipeline_one_way_delay_s": one_way_s,
        "pipeline_microbatches": m,
        "pipeline_overlap_wall_s": round(wall_ov, 3),
        "pipeline_serialized_wall_s": round(wall_sr, 3),
        # the tentpole ratio: DCN round trips overlapped under compute
        "pipeline_overlap_vs_serialized_wall": round(wall_sr / wall_ov, 2),
        "pipeline_bubble_fraction": round(float(bubble), 3),
    }


def _pipeline_dcn_arm(num_microbatches: int = 24, one_way_s: float = 0.05,
                      fwd_floor_s: float = 0.02,
                      bwd_floor_s: float = 0.04,
                      bytes_dim: int = 256, bytes_rows: int = 8,
                      dim: int = 8, mb_rows: int = 4,
                      window: int = 16) -> dict:
    """DCN bytes as a resource: wire compression + interleaved 1F1B.

    Two deterministic sub-arms, both over REAL loopback channels:

    - **bytes-on-wire**: one 2-stage int8-codec training step with
      dim-256 activations; ratio = logical (decoded) send bytes /
      encoded wire bytes, both straight off the channel counters
      (``tony_channel_bytes_total`` vs the codec-only
      ``tony_channel_compressed_bytes_total``). The header is a fixed
      ~100B JSON cost per frame, so the ratio approaches the dtype
      ratio (4x for f32→int8) as tensors grow — at dim 256 it sits
      ~3.9x, tier-1-pinned >= 1.9x.
    - **interleaved vs flat wall**: the SAME 4-block model placed two
      ways across 2 gangs under ``one_way_s`` injected latency
      (LatencyProxy) and fixed per-block compute floors. Flat: gang s
      runs blocks 2s,2s+1 as one stage (one virtual stage per gang,
      in-flight = S). Interleaved (v=2): gang s runs blocks s, s+2 as
      two chunks (looping placement, in-flight = S*v). Little's law:
      steady per-mb rate ≈ max(per-gang compute, cycle/in-flight)
      while latency-bound, where cycle = total compute C + hop
      latencies — flat (0.24+2h)/2 = 0.17 s/mb vs interleaved
      (0.24+6h)/4 = 0.135 s/mb at h = 0.05. C sits a notch below the
      6h crossover so the interleaved rate keeps slack over its 0.12
      s/mb compute floor (thread-scheduling overhead lands in that
      slack, not on the wall); the interleaved fill is ~0.3s longer
      (3 act hops vs 1). Each placement is timed at TWO microbatch
      counts (M and M/3): the marginal rate (wall_big - wall_small)
      / (M - M/3) cancels the fill term exactly, giving the
      steady-state per-mb wall — measured ~1.13x flat/interleaved,
      and stable under load because host jitter inflates both
      placements' rates together. The absolute M-microbatch walls are
      also reported (measured ~1.03-1.07x, fill drag included).
      Losses agree across modes (allclose, not bit-equal: jit
      granularity differs; the BIT pin lives in tests against the
      in-slice V-stage schedule).

    Emits ``pipeline_bytes_on_wire_vs_raw``,
    ``pipeline_interleaved_vs_flat_steady_rate`` and
    ``pipeline_interleaved_vs_flat_wall`` (all tier-1-pinned)."""
    import threading

    import numpy as np

    from tony_tpu.channels import open_local_pipeline
    from tony_tpu.parallel.pipeline import CrossSlicePipeline
    from tony_tpu.runtime import metrics as M
    from tony_tpu.serving.netem import LatencyProxy

    rs = np.random.RandomState(11)
    m = num_microbatches

    def block_fn(p, x):
        return x + jnp.tanh(x @ p["w"] + p["b"])

    def loss_head(hp, out, tgt):
        return jnp.mean((out @ hp["wo"] - tgt) ** 2)

    def mk_block(d):
        return {"w": jnp.asarray(rs.randn(d, d).astype(np.float32) * 0.3),
                "b": jnp.asarray(rs.randn(d).astype(np.float32) * 0.1)}

    # -- sub-arm 1: bytes on the wire under int8 ------------------------
    def run_bytes():
        reg = M.MetricsRegistry()
        links = open_local_pipeline(2, window=window, registry=reg,
                                    compression="int8")
        blocks = [mk_block(bytes_dim) for _ in range(2)]
        head = {"wo": jnp.asarray(
            rs.randn(bytes_dim, bytes_dim).astype(np.float32) * 0.2)}
        xs = jnp.asarray(
            rs.randn(4, bytes_rows, bytes_dim).astype(np.float32))
        tgts = jnp.asarray(
            rs.randn(4, bytes_rows, bytes_dim).astype(np.float32))
        res: dict = {}
        try:
            pls = [CrossSlicePipeline(block_fn, links[0], registry=reg),
                   CrossSlicePipeline(block_fn, links[1],
                                      loss_head=loss_head, registry=reg)]
            ts = [threading.Thread(target=lambda: res.update(
                      a=pls[0].value_and_grad(blocks[0],
                                              num_microbatches=4,
                                              microbatches=xs))),
                  threading.Thread(target=lambda: res.update(
                      b=pls[1].value_and_grad(blocks[1],
                                              num_microbatches=4,
                                              head_params=head,
                                              head_batches=tgts)))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            assert "a" in res and "b" in res
        finally:
            for link in links:
                link.close()
        wire = reg.to_wire()
        logical = sum(v for n, lb, v in wire["c"]
                      if n == "tony_channel_bytes_total"
                      and lb.get("direction") == "send")
        encoded = sum(v for n, lb, v in wire["c"]
                      if n == "tony_channel_compressed_bytes_total"
                      and lb.get("direction") == "send")
        # the codec-only series must be VISIBLE on the metrics plane
        assert encoded > 0, "tony_channel_compressed_bytes_total missing"
        return logical / encoded

    bytes_ratio = run_bytes()

    # -- sub-arm 2: interleaved (v=2) vs flat placement under latency ---
    blocks = [mk_block(dim) for _ in range(4)]
    head = {"wo": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.2)}
    xs = jnp.asarray(rs.randn(m, mb_rows, dim).astype(np.float32))
    tgts = jnp.asarray(rs.randn(m, mb_rows, dim).astype(np.float32))

    def make_floor(fwd_s, bwd_s):
        class FloorPipeline(CrossSlicePipeline):
            def _forward_compute(self, params, x):
                out = super()._forward_compute(params, x)
                jax.block_until_ready(out)
                time.sleep(fwd_s)
                return out

            def _backward_compute(self, params, saved, cot):
                out = super()._backward_compute(params, saved, cot)
                jax.block_until_ready(out)
                time.sleep(bwd_s)
                return out

            def _last_compute(self, params, head_params, saved, head_mb):
                out = super()._last_compute(params, head_params, saved,
                                            head_mb)
                jax.block_until_ready(out)
                time.sleep(fwd_s + bwd_s)
                return out
        return FloorPipeline

    def run_placement(interleave: int):
        reg = M.MetricsRegistry()
        proxies: list[LatencyProxy] = []

        def endpoint_map(stage_idx: int, port: int) -> str:
            proxy = LatencyProxy("127.0.0.1", port, one_way_s)
            proxies.append(proxy)
            return f"127.0.0.1:{proxy.start()}"

        links = open_local_pipeline(2, window=window, capacity=window,
                                    interleave=interleave, registry=reg,
                                    endpoint_map=endpoint_map)
        if interleave == 1:
            # flat: gang s runs blocks 2s,2s+1 fused as ONE stage — its
            # per-mb floor is both blocks' compute
            def stage_fn(p, x):
                return block_fn(p["hi"], block_fn(p["lo"], x))
            Floor = make_floor(2 * fwd_floor_s, 2 * bwd_floor_s)
            gang_params = [{"lo": blocks[0], "hi": blocks[1]},
                           {"lo": blocks[2], "hi": blocks[3]}]
        else:
            # looping placement: gang s chunk j = block j*2+s
            stage_fn = block_fn
            Floor = make_floor(fwd_floor_s, bwd_floor_s)
            gang_params = [[blocks[0], blocks[2]],
                           [blocks[1], blocks[3]]]
        res: dict = {}
        try:
            pls = [Floor(stage_fn, links[0], registry=reg),
                   Floor(stage_fn, links[1], loss_head=loss_head,
                         registry=reg)]

            def one_round(m_run: int) -> float:
                def run0():
                    res[0] = pls[0].value_and_grad(
                        gang_params[0], num_microbatches=m_run,
                        microbatches=xs[:m_run])

                def run1():
                    res[1] = pls[1].value_and_grad(
                        gang_params[1], num_microbatches=m_run,
                        head_params=head, head_batches=jax.tree.map(
                            lambda a: a[:m_run], tgts))
                ts = [threading.Thread(target=run0),
                      threading.Thread(target=run1)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=120)
                return time.perf_counter() - t0

            one_round(4)                    # compile + connect warmup
            wall_small = one_round(m_small)
            wall_big = one_round(m)
            return wall_small, wall_big, res
        finally:
            for link in links:
                link.close()
            for proxy in proxies:
                proxy.stop()

    m_small = max(4, m // 3)
    fl_small, fl_big, res_flat = run_placement(1)
    il_small, il_big, res_il = run_placement(2)
    # same model, two placements: the schedule moves walls, not math
    # (allclose, not bit-equal — flat jits two blocks per stage program)
    np.testing.assert_allclose(np.asarray(res_flat[1][0]),
                               np.asarray(res_il[1][0]),
                               rtol=1e-5, atol=1e-6)
    # steady-state per-microbatch wall: the two-point marginal rate
    # (wall_big - wall_small)/(m - m_small) cancels the pipeline fill —
    # the interleaved fill is ~3x longer (3 act hops vs 1), so the
    # absolute-wall ratio understates the throughput gap and converges
    # to the rate ratio only as M grows
    rate_flat = (fl_big - fl_small) / (m - m_small)
    rate_il = (il_big - il_small) / (m - m_small)
    return {
        "pipeline_bytes_on_wire_vs_raw": round(bytes_ratio, 2),
        "pipeline_flat_wall_s": round(fl_big, 3),
        "pipeline_interleaved_wall_s": round(il_big, 3),
        # the second tentpole ratio: latency hidden by v=2's doubled
        # in-flight, fill excluded (steady-state rates)...
        "pipeline_interleaved_vs_flat_steady_rate":
            round(rate_flat / rate_il, 2),
        # ...and the end-to-end wall at M microbatches, fill included
        "pipeline_interleaved_vs_flat_wall": round(fl_big / il_big, 2),
    }


if __name__ == "__main__":
    main()
