"""Benchmark: flagship transformer train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no performance numbers (BASELINE.md: "published":
{}), so ``vs_baseline`` is measured in-run against the naive formulation of
the same model — dense O(S²) attention and no fused kernels — i.e. what a
line-for-line port of a CUDA/torch-style model to jax would do. Values > 1
mean the framework's TPU-first path (flash-attention pallas kernels, bf16
MXU matmuls, fused norms) beats the naive port on the same hardware.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp

# Peak dense bf16 FLOP/s per chip, keyed by substring of device_kind.
# Order matters: more specific names first ("v5 lite" before "v5").
_PEAK_FLOPS = (
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def _peak_flops() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    for name, peak in _PEAK_FLOPS:
        if name in kind:
            return peak
    return None


def _bench_step(step, state, batch, iters: int, reps: int = 3) -> float:
    """Median-of-windows step time. The shared/tunneled chip's effective
    speed drifts ±15% across seconds (docs/performance.md measurement
    hygiene); a single window can record a bad minute as the framework's
    throughput, so each config is timed over ``reps`` windows and the
    median wins. Host value fetch, not block_until_ready: on tunneled
    platforms the latter can return before execution finishes, faking
    microsecond steps."""
    state, m = step(state, batch)            # compile + warm
    float(m["loss"])
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, batch)
        float(m["loss"])
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]


def main() -> None:
    import os
    # ~2/3 of a cold bench run is XLA compilation (6 jitted programs); the
    # persistent cache makes repeat runs start measuring immediately.
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    from tony_tpu.models import transformer as T
    from tony_tpu.models.train import (default_optimizer, init_state,
                                       make_train_step)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # 512d/8L bf16, seq 1024. remat off (this size fits HBM comfortably
        # on one chip, ~7% faster), layers fully unrolled (drops the
        # scan's activation-stacking DUS ops, ~6% faster; compile cost is
        # paid once), batch 32 (+12% over 16 in interleaved A/B once bf16
        # logits storage freed the headroom).
        cfg = T.PRESETS["small"].scaled(remat=False, scan_unroll=8)
        batch, seq, iters = 32, 1024, 20
    else:                                    # CPU smoke fallback
        cfg = T.PRESETS["tiny"].scaled(dtype=jnp.float32)
        batch, seq, iters = 2, 128, 3

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)
    data = {"inputs": tokens[:, :seq], "targets": tokens[:, 1:]}

    def run(config, run_data, run_iters, reps=3) -> float:
        params = T.init_params(jax.random.PRNGKey(0), config)
        opt = default_optimizer(lr=1e-3)
        state = init_state(params, opt)
        step = make_train_step(
            lambda p, b: T.lm_loss(p, b, config), opt)
        return _bench_step(step, state, run_data, run_iters, reps=reps)

    t_framework = run(cfg, data, iters)

    # Naive port baseline: f32 params/compute, dense attention (remat off so
    # it is the straight autodiff graph a naive port gets). Run at batch 8 —
    # the naive formulation's own best config: at batch 16 its f32 dense
    # attention residuals blow past HBM and it collapses pathologically,
    # which would flatter vs_baseline. Compare per-token throughput.
    import tony_tpu.models.transformer as tmod
    naive_cfg = cfg.scaled(dtype=jnp.float32, remat=False)
    n_batch = min(batch, 8)
    n_data = {k: v[:n_batch] for k, v in data.items()}
    orig = tmod._attention
    tmod._attention = lambda q, k, v, *a: tmod.reference_attention(
        q, k, v, causal=True)
    try:
        # 2 windows: the RATIO tolerates drift better than absolute numbers
        t_naive = run(naive_cfg, n_data, iters, reps=2)
    finally:
        tmod._attention = orig

    tokens_per_sec = batch * seq / t_framework
    naive_tokens_per_sec = n_batch * seq / t_naive
    out = {
        "metric": "flagship_lm_train_throughput",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / naive_tokens_per_sec, 3),
    }

    peak = _peak_flops()
    if peak is not None:
        flops_tok = T.train_flops_per_token(cfg, seq)
        out["mfu"] = round(tokens_per_sec * flops_tok / peak, 4)
        out["device"] = jax.devices()[0].device_kind

    if on_tpu:
        # Secondary: KV-cache autoregressive decode throughput (the serving
        # path: prefill + scan-decode as one compiled program).
        from tony_tpu.models.decode import generate
        d_batch, d_prompt, d_new = 16, 128, 256
        params = T.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(3),
                                    (d_batch, d_prompt), 0, cfg.vocab_size)
        # generate is already jit-compiled (static cfg/lengths)
        gen = functools.partial(generate, cfg=cfg, max_new_tokens=d_new,
                                temperature=0.0)
        dec = gen(params, prompt, rng=jax.random.PRNGKey(4))
        int(dec.tokens[0, 0])                    # compile + warm
        t0 = time.perf_counter()
        for i in range(3):
            dec = gen(params, prompt, rng=jax.random.PRNGKey(5 + i))
        int(dec.tokens[0, 0])
        t_dec = (time.perf_counter() - t0) / 3
        decode_tps = round(d_batch * d_new / t_dec, 1)
        # GQA decode (n_kv_heads=2): the grouped cache read + GQA-native
        # prefill kernels cut the decode-roofline HBM traffic — recorded
        # as its own arm since the model differs from the MHA flagship.
        gqa_cfg = cfg.scaled(n_kv_heads=2)
        gqa_params = T.init_params(jax.random.PRNGKey(0), gqa_cfg)
        gqa_gen = functools.partial(generate, cfg=gqa_cfg,
                                    max_new_tokens=d_new, temperature=0.0)
        dec = gqa_gen(gqa_params, prompt, rng=jax.random.PRNGKey(4))
        int(dec.tokens[0, 0])                    # compile + warm
        t0 = time.perf_counter()
        for i in range(3):
            dec = gqa_gen(gqa_params, prompt, rng=jax.random.PRNGKey(9 + i))
        int(dec.tokens[0, 0])
        decode_gqa_tps = round(d_batch * d_new * 3
                               / (time.perf_counter() - t0), 1)
        out["decode_gqa_tokens_per_s"] = decode_gqa_tps
        del gqa_params, gqa_gen
        del params, prompt, dec, gen   # free HBM before the tight base run

        def secondary(name, config, s_batch, s_seq, s_iters, key,
                      with_mfu=True):
            toks = jax.random.randint(jax.random.PRNGKey(key),
                                      (s_batch, s_seq + 1), 0,
                                      config.vocab_size)
            s_data = {"inputs": toks[:, :s_seq], "targets": toks[:, 1:]}
            tps = s_batch * s_seq / run(config, s_data, s_iters, reps=2)
            out[f"{name}_tokens_per_s"] = round(tps, 1)
            if with_mfu and peak is not None:
                out[f"{name}_mfu"] = round(
                    tps * T.train_flops_per_token(config, s_seq) / peak, 4)

        # GQA flagship (n_kv_heads=2): the grouped-query training win the
        # GQA-native kernels buy (K/V projections + attention K/V reads
        # ÷4). MFU accounting is GQA-aware (train_flops_per_token).
        secondary("gqa", cfg.scaled(n_kv_heads=2), batch, seq, 15, key=8)
        # "base" preset (768d/12L, BERT-base scale) at seq 2048 — stresses
        # framework overheads the small preset doesn't. remat off fits at
        # batch 8 on 16G HBM and is ~25% faster than remat at b=4.
        secondary("base", T.PRESETS["base"].scaled(remat=False,
                                                   scan_unroll=12),
                  8, 2048, 10, key=2)
        out["decode_tokens_per_s"] = decode_tps
        # "large" preset (1536d/24L, 1.0B params) — remat on (the optimizer
        # state already takes ~8 GB of HBM); the bigger matmuls give the
        # best MFU of any preset.
        secondary("large", T.PRESETS["large"], 4, 1024, 8, key=7)
        # long context (seq 8192) — the regime where attention dominates
        # layer FLOPs. Batch 4 is ~4% over 2 (interleaved A/B) and fits.
        # MFU recorded so the fused-vs-two-pass backward budget decision
        # (ops/attention.py _FUSED_PARTIALS_BYTES) has an efficiency
        # number to regress against.
        secondary("seq8k", cfg, 4, 8192, 10, key=6)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
