"""Typed inter-gang tensor channels: persistent point-to-point transport
over DCN.

The data path that lets two gangs cooperate on ONE model: a pipeline
stage gang streams activations forward (and cotangents backward) to its
neighbor stage's gang without the coordinator in the loop. The wire
reuses the TONYS1 length-prefixed framing discipline from
``tony_tpu/serving/protocol.py`` (magic preamble, explicit length
prefix, JSON handshake) with its own magic and frame set — this is a
tensor plane, not a token plane, and a stray cross-plane connection must
fail at the first byte.

Connection handshake (the SENDER dials the receiving task's hub)::

    sender   -> receiver   magic  b"TONYC1\\0"
    sender   -> receiver   HELLO frame, JSON {"v": 1, "channel": name}
    receiver -> sender     HELLO frame, JSON {"v": 1, "resume": seq}

``resume`` is the receiver's next expected sequence number — on a fresh
channel it is 0; after a transient socket loss the sender reconnects,
learns where the receiver actually is, drops everything already
delivered and resends the rest. Sequence numbers ride the frame's
``rid`` field, so TENSOR frames need no extra header field for them.

Frame types (framing itself is protocol.py's: u32 length, u8 type,
u64 rid):

======== ============ =========================================
 type     direction    payload
======== ============ =========================================
CH_HELLO  both         JSON (see handshake above)
CH_TENSOR s -> r       u32 header_len + JSON header
                       ``{"dtype": str, "shape": [ints]}`` + raw
                       C-contiguous buffer bytes
CH_ACK    r -> s       (empty) — ``rid`` = highest in-order seq
                       consumed; advances the sender's window
CH_ERROR  r -> s       JSON ``{"message": str}`` — the receiver is
                       closing THIS connection (garbage frame, seq
                       gap); channel state survives, the sender
                       reconnects and resumes
======== ============ =========================================

Wire compression (``codec`` = ``"bf16"`` | ``"int8"``): a channel may
negotiate an on-the-wire codec at the handshake — the sender's HELLO
carries ``"codec"`` and the hub refuses (CH_ERROR, permanent: the
sender raises ChannelError instead of retrying into garbled math) when
it disagrees with what the consumer declared. Tensor headers on a codec
channel are KIND-TAGGED with the same ``"codec"`` field, so a
compressed frame on a raw channel — or a raw frame on a codec channel —
is a ProtocolError at decode, never a silently misread buffer (the
``TEMPLATE_KIND`` discipline of serving/kvship.py). f32/bf16 payloads
ship as bf16 halves or int8+per-tensor-scale (~quarter of f32); every
other dtype passes through raw under the tag. The codec runs BEFORE the
send window, so the resend buffer holds only the encoded bytes — window
host memory shrinks with the wire.

Reliability/backpressure contract:

- **Bounded send window**: at most ``window`` unacked TENSOR frames in
  flight; ``send`` blocks past that instead of buffering unboundedly —
  a stalled consumer stage backpressures its producer stage through
  TCP + the window, never through host memory.
- **Exactly-once delivery to the consumer**: the receiver acks in
  order and drops duplicates below its resume point, so a reconnect
  never duplicates or drops a microbatch.
- **Channel-scoped failure**: a truncated or garbage frame costs only
  the offending connection (best-effort CH_ERROR, close); the hub
  keeps serving its other channels and the peer reconnects with seq
  resume.

Everything here is transport-only (stdlib + numpy, no jax): importable
by trainers, the coordinator's registry, the bench, and tests alike.
"""

from __future__ import annotations

import json
import math
import os
import socket
import struct
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.serving.protocol import (BODY_HEADER_BYTES, ProtocolError,
                                       frame_header, pack_json, recv_exact,
                                       recv_frame, send_frame, set_nodelay,
                                       unpack_json)

CH_MAGIC = b"TONYC1\0"

CH_HELLO = 1
CH_TENSOR = 2
CH_ACK = 3
CH_ERROR = 4

_HLEN = struct.Struct("<I")     # tensor-header length prefix

#: the tensor plane's own frame cap — far above the serving plane's
#: MAX_FRAME_BYTES (16 MiB of tokens is corruption; 16 MiB of
#: activations is a small microbatch). One frame = one microbatch
#: tensor; past this the SENDER fails fast with ChannelError rather
#: than shipping something the peer will reject.
MAX_TENSOR_BYTES = 1 << 31

#: magic prefix of every chunked byte-blob frame (see
#: :meth:`ChannelSender.send_bytes`): a blob larger than the chunk
#: budget ships as one MANIFEST frame — this magic + a u32-length-
#: prefixed JSON header ``{"v":2,"kind":"manifest","chunks":N,
#: "total":T,"blob":id}`` — followed by N bounded CHUNK frames, each
#: the same magic + header ``{"v":2,"kind":"chunk","blob":id,"i":i}``
#: + payload bytes. Each frame is an ordinary seq-numbered tensor
#: frame, so a disconnect mid-blob resumes at the first unacked CHUNK,
#: not the whole blob (zero duplicated / dropped bytes — test-pinned).
#: The per-frame kind tag + per-blob id are what let a receiver that
#: ABORTED a reassembly (chunk timeout, seeder death) re-synchronize:
#: a stale chunk of the dead blob is identified and discarded, never
#: misparsed as a standalone blob. A raw blob that happens to START
#: with this magic is escaped into a single-chunk envelope so the
#: receiver can never misparse it.
BLOB_CHUNK_MAGIC = b"TONYB1\0"

#: per-chunk recv deadline while reassembling a chunked blob (see
#: :meth:`ChannelReceiver.recv_bytes`): once a manifest has arrived
#: the receiver is committed to the blob, so each chunk gets its own
#: generous deadline instead of whatever sliver remains of the
#: caller's first-frame timeout — a multi-GB artifact backpressured
#: through a small hub must never be aborted mid-reassembly by an
#: idle-poll timeout. Transient disconnects are invisible here (the
#: sender reconnects and seq-resumes); only a truly dead sender makes
#: a chunk wait this long.
BLOB_CHUNK_TIMEOUT_S = 60.0

#: default chunk budget for :meth:`ChannelSender.send_bytes` (the
#: ``tony.weights.chunk-bytes`` config key feeds callers that override
#: it). 8 MiB keeps resend-on-reconnect work bounded while staying far
#: above per-frame overhead.
DEFAULT_BLOB_CHUNK_BYTES = 8 << 20

#: sanity cap on a chunked blob's manifest (an envelope promising
#: billions of chunks is a corrupt or adversarial frame, refused
#: before the receiver commits to gathering them).
MAX_BLOB_CHUNKS = 1 << 20

#: send/recv wait buckets: DCN one-way latencies are milliseconds, a
#: window stall can reach seconds — finer than the generic time ladder
#: at the low end.
CHANNEL_WAIT_BUCKETS_S: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0)


class ChannelError(ConnectionError):
    """The channel is unusable from this endpoint's point of view:
    closed, past its reconnect budget, or a wait timed out. Transient
    socket loss is NOT surfaced as this — senders reconnect and resume
    internally."""


class ChannelClosed(ChannelError):
    """The receiving hub has STOPPED: no delivery can ever succeed on
    this endpoint again — distinct from a recv timeout, so a consumer
    loop can exit instead of hot-spinning on instant failures."""


#: valid per-channel wire codecs (tony.channel.compression values).
CODECS = ("none", "bf16", "int8")

#: dtypes a codec actually compresses; everything else passes through
#: raw under the codec kind-tag (ints/bools must stay exact).
_COMPRESSIBLE = ("float32", "bfloat16")

_SCALE = struct.Struct("<f")    # int8 per-tensor scale, payload prefix

#: exactness-guard flag (see :func:`forbid_codecs`).
_CODECS_FORBIDDEN = False


def forbid_codecs(on: bool) -> None:
    """Arm (or disarm) the bit-exactness guard: while armed, building a
    sender or receiver with a non-"none" codec raises RuntimeError. The
    test harness arms this inside bit-identity-pinned tests (pytest
    marker ``exact``), so a stray quantized channel in an exactness pin
    fails loudly instead of flaking the comparison."""
    global _CODECS_FORBIDDEN
    _CODECS_FORBIDDEN = on


def _check_codec(codec: str, what: str) -> str:
    if codec not in CODECS:
        raise ValueError(f"unknown channel codec {codec!r} for {what}; "
                         f"expected one of {CODECS}")
    if codec != "none" and _CODECS_FORBIDDEN:
        raise RuntimeError(
            f"quantized channel codec {codec!r} constructed for {what} "
            f"inside a bit-exactness-pinned context (channels."
            f"forbid_codecs) — exactness tests must run uncompressed")
    return codec


def _np_dtype(name: str) -> np.dtype:
    """dtype-by-name with the ml_dtypes fallback for bfloat16 (numpy
    alone cannot name it; ml_dtypes rides in with jax) — the same
    resolution kvship uses for shipped KV buffers."""
    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes
            return np.dtype(getattr(ml_dtypes, name))
        except (ImportError, AttributeError) as e:
            raise ProtocolError(f"unknown TENSOR dtype {name!r}") from e


def _bf16_dtype() -> np.dtype:
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


def encode_tensor(arr: np.ndarray, codec: str = "none") \
        -> tuple[bytes, bytes]:
    """-> (tensor header bytes, encoded payload bytes). The payload is
    what the wire carries AND what the sender's resend window retains —
    the codec runs here, before windowing, so a compressed channel's
    window holds the small encoded buffer, never the f32 original.

    codec "none" keeps the original wire format (header
    ``{"dtype", "shape"}``, raw C-contiguous bytes). A real codec
    kind-tags the header with ``"codec"`` plus the on-wire layout
    (``"wire"``: "bf16" / "int8" / "raw" passthrough) while ``"dtype"``
    stays the ORIGINAL dtype the receiver must restore."""
    arr = np.asarray(arr)
    # shape captured FIRST: ascontiguousarray promotes 0-d to 1-d
    shape = list(arr.shape)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    if codec == "none":
        head = pack_json({"dtype": str(arr.dtype), "shape": shape})
        return _HLEN.pack(len(head)) + head, arr.tobytes()
    if codec == "bf16":
        if str(arr.dtype) in _COMPRESSIBLE:
            wire, raw = "bf16", \
                np.ascontiguousarray(arr.astype(_bf16_dtype())).tobytes()
        else:
            wire, raw = "raw", arr.tobytes()
    elif codec == "int8":
        if str(arr.dtype) in _COMPRESSIBLE:
            a = arr.astype(np.float32, copy=False)
            amax = float(np.max(np.abs(a))) if a.size else 0.0
            scale = amax / 127.0 if amax > 0.0 else 1.0
            q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
            wire, raw = "int8", _SCALE.pack(scale) + q.tobytes()
        else:
            wire, raw = "raw", arr.tobytes()
    else:
        raise ValueError(f"unknown channel codec {codec!r}")
    head = pack_json({"codec": codec, "wire": wire,
                      "dtype": str(arr.dtype), "shape": shape})
    return _HLEN.pack(len(head)) + head, raw


def decode_tensor(payload: bytes, codec: str = "none") -> np.ndarray:
    """Parse a CH_TENSOR payload back into an ndarray under the
    channel's negotiated ``codec``. Anything structurally off is a
    ProtocolError (channel-scoped) — including a KIND-TAG mismatch: a
    compressed frame on a raw channel, a raw frame on a codec channel,
    or a frame tagged with a different codec than negotiated can never
    silently misread each other's bytes."""
    if len(payload) < _HLEN.size:
        raise ProtocolError("TENSOR frame shorter than its header prefix")
    (hlen,) = _HLEN.unpack_from(payload, 0)
    if _HLEN.size + hlen > len(payload):
        raise ProtocolError(f"TENSOR header length {hlen} exceeds frame")
    head = unpack_json(payload[_HLEN.size:_HLEN.size + hlen])
    tag = head.get("codec")
    if codec == "none":
        if tag is not None:
            raise ProtocolError(
                f"compressed frame (codec {tag!r}) on a raw channel")
    elif tag != codec:
        raise ProtocolError(
            f"frame kind-tag {tag!r} on a channel negotiated for "
            f"codec {codec!r}"
            + (" (raw frame on a codec channel)" if tag is None else ""))
    shape = head.get("shape")
    dtype = head.get("dtype")
    if not isinstance(shape, list) or not all(
            isinstance(d, int) and not isinstance(d, bool) and d >= 0
            for d in shape) or not isinstance(dtype, str):
        raise ProtocolError(f"malformed TENSOR header: {head!r}")
    dt = _np_dtype(dtype)
    raw = payload[_HLEN.size + hlen:]
    # python-int math: np.prod wraps on adversarial shapes, letting a
    # bogus length claim past the check into a reshape crash
    count = math.prod(shape)
    if codec == "none":
        wire = "raw"
    else:
        wire = head.get("wire")
        if wire not in ("raw", "bf16", "int8"):
            raise ProtocolError(f"malformed TENSOR wire layout {wire!r}")
        if wire != "raw" and dtype not in _COMPRESSIBLE:
            raise ProtocolError(
                f"codec wire {wire!r} cannot restore dtype {dtype!r}")
    if wire == "raw":
        want = count * dt.itemsize
        if len(raw) != want:
            raise ProtocolError(
                f"TENSOR payload {len(raw)} bytes, header promises {want}")
        return np.frombuffer(raw, dtype=dt).reshape(shape)
    if wire == "bf16":
        want = count * 2
        if len(raw) != want:
            raise ProtocolError(
                f"bf16 payload {len(raw)} bytes, header promises {want}")
        return np.frombuffer(raw, dtype=_bf16_dtype()) \
            .astype(dt).reshape(shape)
    # wire == "int8": per-tensor f32 scale prefix + int8 values — a
    # truncated scale (or a length off by even one value byte) must
    # fail structurally, never decode shifted garbage
    want = _SCALE.size + count
    if len(raw) != want:
        raise ProtocolError(
            f"int8 payload {len(raw)} bytes, header promises {want} "
            f"(scale prefix + values)")
    (scale,) = _SCALE.unpack_from(raw, 0)
    if not math.isfinite(scale):
        raise ProtocolError(f"non-finite int8 scale {scale!r}")
    q = np.frombuffer(raw, dtype=np.int8, offset=_SCALE.size)
    return (q.astype(np.float32) * np.float32(scale)) \
        .astype(dt).reshape(shape)


def _blob_frame(head: dict, payload: bytes = b"") -> bytes:
    """Serialize one chunked-blob frame: magic + u32 header length +
    compact-JSON header + payload bytes."""
    h = json.dumps(head, separators=(",", ":")).encode("utf-8")
    return BLOB_CHUNK_MAGIC + _HLEN.pack(len(h)) + h + payload


def _parse_blob_frame(buf: bytes) -> tuple[dict, bytes]:
    """Split a magic-prefixed chunked-blob frame into (header dict,
    payload bytes); structurally-off frames are ProtocolError."""
    off = len(BLOB_CHUNK_MAGIC)
    if len(buf) < off + _HLEN.size:
        raise ProtocolError(
            "chunked-blob frame shorter than its header prefix")
    (hlen,) = _HLEN.unpack_from(buf, off)
    if off + _HLEN.size + hlen > len(buf):
        raise ProtocolError(
            f"chunked-blob header length {hlen} exceeds frame")
    try:
        head = json.loads(
            buf[off + _HLEN.size:off + _HLEN.size + hlen]
            .decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"malformed chunked-blob header: {e}") from e
    if not isinstance(head, dict):
        raise ProtocolError(f"malformed chunked-blob header: {head!r}")
    return head, buf[off + _HLEN.size + hlen:]


def _check_manifest(head: dict, payload: bytes) -> tuple[int, int, str]:
    """Validate a manifest header -> (chunks, total, blob id)."""
    chunks = head.get("chunks")
    total = head.get("total")
    blob_id = head.get("blob")
    if (isinstance(chunks, bool) or not isinstance(chunks, int)
            or isinstance(total, bool) or not isinstance(total, int)
            or not 1 <= chunks <= MAX_BLOB_CHUNKS or total < 0
            or not isinstance(blob_id, str)):
        raise ProtocolError(
            f"implausible chunked-blob manifest: {head!r}")
    if payload:
        raise ProtocolError(
            f"chunked-blob manifest carries {len(payload)} payload "
            f"bytes (chunks carry the data, the manifest never does)")
    return chunks, total, blob_id


def _send_tensor_frame(sock: socket.socket, seq: int, head: bytes,
                       raw: bytes) -> None:
    """Frame header + tensor header in one small write, the raw buffer
    in a second — the zero-copy discipline of protocol.send_frame's
    large path, without concatenating megabytes per microbatch."""
    sock.sendall(frame_header(CH_TENSOR, seq, len(head) + len(raw),
                              limit=MAX_TENSOR_BYTES) + head)
    sock.sendall(raw)


# ---------------------------------------------------------------------------
# Sender
# ---------------------------------------------------------------------------
class ChannelSender:
    """Dial a peer task's :class:`ChannelHub` and stream tensors with a
    bounded in-flight window and reconnect-with-seq-resume.

    One producer thread calls :meth:`send`; a background reader thread
    consumes acks. ``send`` hands the frame to the OS send buffer and
    returns — the window (not the call) is what overlaps DCN transport
    with the caller's device compute. ``sync=True`` additionally blocks
    until the peer acked the frame (the serialized-baseline mode the
    bench contrasts against)."""

    def __init__(self, address: str, channel: str, *, window: int = 8,
                 codec: str = "none",
                 connect_timeout_s: float = 10.0, max_retries: int = 30,
                 backoff_s: float = 0.05, max_backoff_s: float = 2.0,
                 registry: metrics_mod.MetricsRegistry | None = None) -> None:
        if window < 1:
            raise ValueError(f"channel window must be >= 1, got {window}")
        host, _, port = address.rpartition(":")
        self.address = (host, int(port))
        self.channel = channel
        self.codec = _check_codec(codec, f"sender channel {channel!r}")
        self.window = window
        self.connect_timeout_s = connect_timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._cv = threading.Condition()
        self._sock: socket.socket | None = None
        self._reader: threading.Thread | None = None
        self._broken = True             # no connection yet
        self._closed = False
        self._next_seq = 0
        self._acked_through = -1
        self._unacked: OrderedDict[int, tuple[bytes, bytes]] = OrderedDict()
        self._connected_once = False
        reg = registry or metrics_mod.get_default()
        self._send_hist = reg.histogram(
            "tony_channel_send_seconds",
            help="host wall a channel send spent blocked (serialize + "
                 "window backpressure + socket write)",
            buckets=CHANNEL_WAIT_BUCKETS_S, channel=channel)
        self._depth_gauge = reg.gauge(
            "tony_channel_send_queue_depth",
            help="unacked tensor frames in the sender's window",
            channel=channel)
        self._reconnects = reg.counter(
            "tony_channel_reconnects_total",
            help="sender reconnects after transient socket loss",
            channel=channel)
        self._bytes = reg.counter(
            "tony_channel_bytes_total",
            help="logical (decoded) tensor bytes moved", channel=channel,
            direction="send")
        #: wire bytes actually shipped on a codec channel (header +
        #: encoded payload): bytes_total / compressed_bytes_total is the
        #: live bytes-on-wire compression ratio. Only registered when a
        #: codec is negotiated — raw channels keep their series set.
        self._wire_bytes = None if self.codec == "none" else reg.counter(
            "tony_channel_compressed_bytes_total",
            help="encoded bytes on the wire (codec channels only)",
            channel=channel, direction="send")

    # -- connection management ---------------------------------------------
    def _teardown_locked(self) -> None:
        sock, self._sock = self._sock, None
        self._broken = True
        if sock is not None:
            # shutdown BEFORE close: the ack-reader thread blocked in
            # recv on this fd holds the kernel socket alive, so a bare
            # close() would never send the FIN — the receiver's
            # delivery loop would keep its per-channel conn lock and
            # every later sender's handshake would hang (seen with the
            # short-lived one-ship senders of the prefix template lane)
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _reconnect(self, deadline: float | None = None) -> None:
        """(Re)dial, handshake, fold the receiver's resume point into the
        ack state, resend what it has not seen. Runs on the producer
        thread (the only writer); raises ChannelError past the budget —
        or past ``deadline`` (monotonic), so a caller's send timeout
        bounds the repair attempt too instead of stacking 30 connect
        timeouts on top of it."""
        backoff = self.backoff_s
        last_err: Exception | None = None
        for attempt in range(self.max_retries):
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelError(
                    f"channel {self.channel!r} reconnect to "
                    f"{self.address} timed out: {last_err}")
            with self._cv:
                if self._closed:
                    raise ChannelError(f"channel {self.channel!r} closed")
                if not self._broken:    # another path already fixed it
                    return
            try:
                sock = socket.create_connection(
                    self.address, timeout=self.connect_timeout_s)
            except OSError as e:
                last_err = e
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
                continue
            try:
                set_nodelay(sock)
                sock.sendall(CH_MAGIC)
                hello = {"v": 1, "channel": self.channel}
                if self.codec != "none":    # wire-compat: raw peers
                    hello["codec"] = self.codec     # omit the field
                send_frame(sock, CH_HELLO, 0, pack_json(hello))
                fr = recv_frame(sock)
                if fr is not None and fr[0] == CH_ERROR:
                    # an explicit handshake refusal (codec mismatch) is
                    # PERMANENT: retrying would never converge, and
                    # falling through to raw frames would garble math —
                    # fail channel-scoped right here
                    try:
                        msg = unpack_json(fr[2]).get("message", "")
                    except ProtocolError:
                        msg = ""
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise ChannelError(
                        f"channel {self.channel!r} handshake refused by "
                        f"{self.address}: {msg}")
                if fr is None or fr[0] != CH_HELLO:
                    raise ProtocolError("channel handshake refused")
                resume = unpack_json(fr[2]).get("resume")
                if not isinstance(resume, int) or resume < 0:
                    raise ProtocolError(f"bad resume seq {resume!r}")
                sock.settimeout(None)
            except ChannelError:
                raise               # permanent refusal: not a retry case
            except (OSError, ProtocolError) as e:
                last_err = e
                try:
                    sock.close()
                except OSError:
                    pass
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
                continue
            with self._cv:
                if not self._connected_once and not self._unacked:
                    # a fresh sender adopts the lane's resume point:
                    # nothing of ours can be below it, so seqs start
                    # where the receiver expects them
                    self._next_seq = max(self._next_seq, resume)
                # everything below the resume point was delivered before
                # the cut — retire it; the rest goes out again below
                self._acked_through = max(self._acked_through, resume - 1)
                for seq in [s for s in self._unacked if s < resume]:
                    del self._unacked[seq]
                to_resend = list(self._unacked.items())
                self._sock = sock
                self._broken = False
                if self._connected_once:
                    self._reconnects.inc()
                self._connected_once = True
                self._depth_gauge.set(len(self._unacked))
                self._cv.notify_all()
            try:
                for seq, (head, raw) in to_resend:
                    _send_tensor_frame(sock, seq, head, raw)
            except OSError:
                with self._cv:
                    self._teardown_locked()
                continue
            reader = threading.Thread(
                target=self._reader_loop, args=(sock,),
                name=f"tony-channel-ack-{self.channel}", daemon=True)
            reader.start()
            self._reader = reader
            return
        raise ChannelError(
            f"channel {self.channel!r} to {self.address} unreachable "
            f"after {self.max_retries} attempts: {last_err}")

    def _reader_loop(self, sock: socket.socket) -> None:
        """Consume acks until this connection dies; advancing the ack
        watermark is what releases blocked senders."""
        while True:
            try:
                fr = recv_frame(sock)
            except (ProtocolError, OSError):
                fr = None
            with self._cv:
                if fr is None:
                    if self._sock is sock:      # not already superseded
                        self._teardown_locked()
                    self._cv.notify_all()
                    return
                ftype, seq, payload = fr
                if ftype == CH_ACK:
                    if seq > self._acked_through:
                        self._acked_through = seq
                        for s in [k for k in self._unacked if k <= seq]:
                            del self._unacked[s]
                        self._depth_gauge.set(len(self._unacked))
                        self._cv.notify_all()
                elif ftype == CH_ERROR:
                    # receiver-scoped close (seq gap, decode error): drop
                    # this connection; the producer reconnects + resumes
                    if self._sock is sock:
                        self._teardown_locked()
                    self._cv.notify_all()
                    return

    def _wait(self, pred, timeout: float | None) -> None:
        """Wait under the cv for ``pred``; transparently reconnects when
        the link is down (acks cannot arrive on a dead socket)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not pred():
                if self._closed:
                    raise ChannelError(f"channel {self.channel!r} closed")
                if self._broken:
                    self._cv.release()
                    try:
                        self._reconnect(deadline)
                    finally:
                        self._cv.acquire()
                    continue
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise ChannelError(
                        f"channel {self.channel!r} send wait timed out")
                self._cv.wait(timeout=remaining)

    # -- the producer API ---------------------------------------------------
    def send(self, arr: np.ndarray, *, sync: bool = False,
             timeout: float | None = None) -> int:
        """Queue one tensor; returns its sequence number. Blocks while
        the in-flight window is full (backpressure), and — with
        ``sync=True`` — until the peer acked this frame."""
        t0 = time.perf_counter()
        deadline = None if timeout is None else time.monotonic() + timeout
        arr = np.asarray(arr)
        logical_bytes = arr.nbytes
        # encode BEFORE the window: _unacked retains only the encoded
        # (post-codec) buffer, so resend-window host memory shrinks with
        # the wire instead of pinning window × f32-tensor bytes
        head, raw = encode_tensor(arr, self.codec)
        # mirrors frame_header's limit check exactly (incl. the frame's
        # own header bytes): an oversize frame must fail HERE, before a
        # seq exists — once in _unacked it would poison every reconnect
        if BODY_HEADER_BYTES + len(head) + len(raw) > MAX_TENSOR_BYTES:
            raise ChannelError(
                f"tensor of {len(raw)} bytes exceeds the "
                f"{MAX_TENSOR_BYTES}-byte frame cap — split the "
                f"microbatch")
        # the FIRST connect also happens before a sequence number
        # exists: the handshake's resume point fast-forwards _next_seq
        # (see _reconnect), so a fresh sender joining a lane whose
        # receive state already advanced — short-lived one-ship senders
        # sharing a template lane — numbers its payloads as NEW frames
        # instead of the resume dedup retiring them unsent
        with self._cv:
            never_connected = not self._connected_once
        if never_connected:
            self._reconnect(deadline)
        # window backpressure BEFORE a sequence number exists: a wait
        # that times out here leaves no hole in the seq space (a burned
        # seq would wedge the channel in a permanent gap/reconnect loop)
        self._wait(lambda: len(self._unacked) < self.window, timeout)
        with self._cv:
            if self._closed:
                raise ChannelError(f"channel {self.channel!r} closed")
            seq = self._next_seq
            self._next_seq += 1
            self._unacked[seq] = (head, raw)
            self._depth_gauge.set(len(self._unacked))
            sock = self._sock if not self._broken else None
        if sock is not None:
            try:
                _send_tensor_frame(sock, seq, head, raw)
            except OSError:
                with self._cv:
                    if self._sock is sock:
                        self._teardown_locked()
                # delivery now rides the reconnect resend path — for an
                # async send that is enough; sync waits below
                if not sync:
                    self._reconnect(deadline)
        else:
            # resends the queued frame post-handshake; the caller's
            # timeout bounds the dial too — without the deadline a dead
            # endpoint holds this send for the full retry budget
            self._reconnect(deadline)
        if sync:
            self._wait(lambda: self._acked_through >= seq, timeout)
        self._bytes.inc(logical_bytes)
        if self._wire_bytes is not None:
            self._wire_bytes.inc(len(head) + len(raw))
        self._send_hist.observe(time.perf_counter() - t0)
        return seq

    def send_bytes(self, data, *, sync: bool = False,
                   timeout: float | None = None,
                   chunk_bytes: int | None = None) -> int:
        """Ship an opaque byte blob — the lane structured multi-buffer
        payloads (the serving KV shipment, ``tony_tpu/serving/
        kvship.py``; weight artifacts, ``tony_tpu/serving/
        weightstore.py``) ride without teaching the tensor plane their
        schema. A blob within ``chunk_bytes`` ships as ONE 1-D uint8
        tensor frame; a larger one ships as an envelope frame
        (:data:`BLOB_CHUNK_MAGIC` + manifest) followed by bounded chunk
        frames, each an ordinary seq-numbered frame — so a multi-GB
        blob inherits the window's backpressure and, on disconnect,
        resumes at the first unacked CHUNK instead of resending (or
        worse, dropping) the whole blob. Chunk frames are kind-tagged
        with a per-blob id, so a receiver that aborted a reassembly
        can identify and discard the dead blob's stragglers. Same
        window/reconnect/ordering contract as :meth:`send`; pair with
        :meth:`ChannelReceiver.recv_bytes`. Returns the seq of the
        blob's LAST frame (what ``sync=True`` waits on)."""
        data = bytes(data) if not isinstance(data, (bytes, bytearray,
                                                    memoryview)) else data
        view = memoryview(data)
        limit = chunk_bytes if chunk_bytes is not None \
            else DEFAULT_BLOB_CHUNK_BYTES
        if limit < 1:
            raise ValueError(f"chunk_bytes must be >= 1, got {limit}")
        magic_collision = view[:len(BLOB_CHUNK_MAGIC)] == BLOB_CHUNK_MAGIC
        if len(view) <= limit and not magic_collision:
            return self.send(np.frombuffer(view, dtype=np.uint8),
                             sync=sync, timeout=timeout)
        # chunked path: manifest first, then the chunks. Only the LAST
        # frame honours sync — in-order exactly-once delivery means the
        # last ack implies every earlier chunk landed.
        chunks = max(1, -(-len(view) // limit))
        blob_id = os.urandom(8).hex()   # names THIS transfer: a stale
        # chunk surviving an aborted reassembly can never be mistaken
        # for part of a later blob (even a byte-identical re-ship)
        envelope = _blob_frame({"v": 2, "kind": "manifest",
                                "chunks": chunks, "total": len(view),
                                "blob": blob_id})
        deadline = None if timeout is None else time.monotonic() + timeout
        def left() -> float | None:
            return None if deadline is None \
                else max(0.0, deadline - time.monotonic())
        self.send(np.frombuffer(envelope, dtype=np.uint8), sync=False,
                  timeout=left())
        seq = -1
        for i in range(chunks):
            frame = _blob_frame({"v": 2, "kind": "chunk",
                                 "blob": blob_id, "i": i},
                                bytes(view[i * limit:(i + 1) * limit]))
            last = i == chunks - 1
            seq = self.send(np.frombuffer(frame, dtype=np.uint8),
                            sync=sync and last, timeout=left())
        return seq

    def drain(self, timeout: float | None = None) -> None:
        """Block until every sent frame is acked."""
        with self._cv:
            last = self._next_seq - 1
        if last >= 0:
            self._wait(lambda: self._acked_through >= last, timeout)

    def unacked(self) -> int:
        with self._cv:
            return len(self._unacked)

    def window_bytes(self) -> int:
        """Host bytes the resend window currently retains (encoded
        header + payload per in-flight frame) — what a codec ≈ halves;
        pinned by the window-memory test."""
        with self._cv:
            return sum(len(h) + len(r) for h, r in self._unacked.values())

    def close(self, drain: bool = True,
              timeout: float | None = 30.0) -> None:
        if drain and not self._closed:
            try:
                self.drain(timeout)
            except ChannelError:
                pass            # best-effort: closing anyway
        with self._cv:
            self._closed = True
            self._teardown_locked()
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# Receiver hub
# ---------------------------------------------------------------------------
class _RecvState:
    """Per-channel receive state: survives connections, so a reconnecting
    sender resumes exactly where the consumer is."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        #: the channel's negotiated wire codec: None until the consumer
        #: (hub.receiver) or the first sender HELLO declares one; every
        #: later declarer must MATCH or is refused channel-scoped.
        self.codec: str | None = None
        self.next_seq = 0
        self.queue: deque[np.ndarray] = deque()
        self.cv = threading.Condition()
        self.closed = False
        #: ONE delivering connection at a time (held from the resume
        #: reply through the read loop): a predecessor connection still
        #: blocked mid-``put`` must finish — settling ``next_seq`` —
        #: before a reconnecting sender is told where to resume, or its
        #: seq would be delivered twice and the following one dropped.
        self.conn_lock = threading.Lock()
        #: the connection currently entitled to deliver. A NEW
        #: connection for the channel PREEMPTS the old one (closes its
        #: socket so a half-open predecessor's blocked read errors out
        #: and releases conn_lock) instead of queueing behind it forever.
        self.active_sock: object = None
        self.active_lock = threading.Lock()

    def put(self, arr: np.ndarray) -> bool:
        """Enqueue one in-order tensor; blocks while the consumer is
        ``capacity`` behind (the ack is withheld too, so the sender's
        window backpressures through here). False once closed."""
        with self.cv:
            while len(self.queue) >= self.capacity and not self.closed:
                self.cv.wait()
            if self.closed:
                return False
            self.queue.append(arr)
            self.next_seq += 1
            self.cv.notify_all()
            return True

    def get(self, timeout: float | None) -> np.ndarray:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cv:
            while not self.queue:
                if self.closed:
                    raise ChannelClosed("channel hub stopped")
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise ChannelError("channel recv timed out")
                self.cv.wait(timeout=remaining)
            arr = self.queue.popleft()
            self.cv.notify_all()
            return arr

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()


class ChannelReceiver:
    """Consumer facade over one named channel of a :class:`ChannelHub`."""

    def __init__(self, hub: "ChannelHub", name: str,
                 state: _RecvState) -> None:
        self._hub = hub
        self.name = name
        self._state = state
        #: seq of the most recently consumed tensor, derived from the
        #: SHARED channel state at recv time (next_seq counts delivered-
        #: to-queue; minus what is still queued = consumed) — the same
        #: number the sender's ``send`` returned for that tensor, which
        #: is what lets both ends tag one microbatch's trace spans with
        #: one shared seq without any extra frames. Derived, not a
        #: per-facade counter: a second facade over the same channel
        #: state stays correct.
        self._last_seq = -1
        reg = hub._registry
        self._wait_hist = reg.histogram(
            "tony_channel_recv_wait_seconds",
            help="host wall a channel recv spent blocked on the wire",
            buckets=CHANNEL_WAIT_BUCKETS_S, channel=name)
        self._depth_gauge = reg.gauge(
            "tony_channel_recv_queue_depth",
            help="tensors buffered ahead of the consumer", channel=name)

    def recv(self, timeout: float | None = None) -> np.ndarray:
        t0 = time.perf_counter()
        arr = self._state.get(timeout)
        self._wait_hist.observe(time.perf_counter() - t0)
        with self._state.cv:
            self._depth_gauge.set(len(self._state.queue))
            # consumed = delivered-to-queue minus still-queued; -1 for
            # "seq of the one just popped"
            self._last_seq = self._state.next_seq \
                - len(self._state.queue) - 1
        return arr

    def recv_bytes(self, timeout: float | None = None,
                   chunk_timeout: float | None = None) -> bytes:
        """Consume one opaque byte blob (the :meth:`ChannelSender.
        send_bytes` counterpart) — reassembling a chunked blob
        (:data:`BLOB_CHUNK_MAGIC` manifest + tagged chunk frames) back
        into the exact sent bytes.

        ``timeout`` bounds the wait for the blob's FIRST frame only —
        the idle-poll budget. Once a manifest arrives the reassembly
        is committed, and each chunk frame gets its own
        ``chunk_timeout`` (default :data:`BLOB_CHUNK_TIMEOUT_S`)
        instead of whatever remains of the caller's budget: an install
        loop polling at 250 ms must never abort a multi-GB transfer
        that takes seconds to backpressure through the hub. A chunk
        that truly never arrives (seeder death — transient disconnects
        seq-resume invisibly) raises ChannelError mid-reassembly;
        stale chunks the dead blob already queued are identified by
        their blob id and DISCARDED on the next call, so the lane
        re-synchronizes instead of misparsing them as standalone
        blobs. A fresh manifest arriving mid-reassembly restarts the
        reassembly on the new blob (the sender gave up and re-shipped).

        A frame that is not a 1-D uint8 tensor is a peer speaking the
        wrong sub-protocol — surfaced as ProtocolError so the consumer
        can scope it, never silently reinterpreted bytes."""
        deadline = None if timeout is None else time.monotonic() + timeout
        def left() -> float | None:
            return None if deadline is None \
                else max(0.0, deadline - time.monotonic())
        per_chunk = BLOB_CHUNK_TIMEOUT_S if chunk_timeout is None \
            else chunk_timeout

        def byte_frame(waiting: float | None, what: str) -> bytes:
            arr = self.recv(waiting)
            if arr.dtype != np.uint8 or arr.ndim != 1:
                raise ProtocolError(
                    f"expected {what} (1-D uint8), got "
                    f"{arr.dtype}{list(arr.shape)}")
            return arr.tobytes()

        # wait for the blob to START (the only wait the caller's
        # timeout bounds), discarding stragglers of any aborted blob
        while True:
            first = byte_frame(left(), "a byte-blob frame")
            if not first.startswith(BLOB_CHUNK_MAGIC):
                return first
            head, payload = _parse_blob_frame(first)
            kind = head.get("kind")
            if kind == "chunk":
                continue    # orphan of an aborted reassembly: discard
            if kind != "manifest":
                raise ProtocolError(
                    f"unknown chunked-blob frame kind {kind!r}")
            break
        while True:     # one iteration per manifest (restart on a new one)
            chunks, total, blob_id = _check_manifest(head, payload)
            parts: list[bytes] = []
            got = 0
            restarted = False
            while len(parts) < chunks:
                b = byte_frame(per_chunk,
                               f"chunk {len(parts)}/{chunks} of blob "
                               f"{blob_id}")
                if not b.startswith(BLOB_CHUNK_MAGIC):
                    raise ProtocolError(
                        f"untagged frame interleaved mid-reassembly of "
                        f"blob {blob_id} ({len(parts)}/{chunks} chunks "
                        f"landed)")
                chead, cpayload = _parse_blob_frame(b)
                ckind = chead.get("kind")
                if ckind == "manifest":
                    # the sender abandoned this blob and started over
                    head, payload = chead, cpayload
                    restarted = True
                    break
                if ckind != "chunk":
                    raise ProtocolError(
                        f"unknown chunked-blob frame kind {ckind!r}")
                if chead.get("blob") != blob_id:
                    continue    # stale chunk of an aborted blob
                if chead.get("i") != len(parts):
                    raise ProtocolError(
                        f"blob {blob_id} chunk out of order: got "
                        f"{chead.get('i')!r}, expected {len(parts)}")
                got += len(cpayload)
                if got > total:
                    raise ProtocolError(
                        f"chunked blob overflows its manifest: chunk "
                        f"{len(parts)} brings {got} bytes past the "
                        f"promised {total}")
                parts.append(cpayload)
            if restarted:
                continue
            if got != total:
                raise ProtocolError(
                    f"chunked blob reassembled to {got} bytes, manifest "
                    f"promised {total}")
            return b"".join(parts)

    @property
    def last_seq(self) -> int:
        """Seq of the most recently consumed tensor (-1 before any)."""
        return self._last_seq

    def qsize(self) -> int:
        with self._state.cv:
            return len(self._state.queue)


class ChannelHub:
    """One listening endpoint per task, multiplexing every inbound
    channel by name. Senders dial it; a connection's HELLO names the
    channel it carries. Connection loss (or a garbage frame) never
    touches channel state — the reconnecting sender's handshake learns
    ``next_seq`` and resumes."""

    def __init__(self, port: int = 0, *, capacity: int = 8,
                 bind_host: str = "",
                 registry: metrics_mod.MetricsRegistry | None = None) -> None:
        self.port = port
        self.capacity = capacity
        self.bind_host = bind_host
        self._registry = registry or metrics_mod.get_default()
        self._server: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._states: dict[str, _RecvState] = {}
        self._states_lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._stopping = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((self.bind_host, self.port))
        server.listen(16)
        self.port = server.getsockname()[1]
        self._server = server
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tony-channel-hub", daemon=True)
        self._accept_thread.start()
        return self.port

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            # shutdown wakes an accept() blocked in another thread —
            # a bare close() does not (the blocked syscall pins the
            # fd), which left stop() burning the accept-thread join
            # timeout (the FrameServerBase listener does the same)
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        self.disconnect_all()
        with self._states_lock:
            for state in self._states.values():
                state.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def disconnect_all(self) -> None:
        """Sever every live connection WITHOUT touching channel state —
        the fault-injection hook behind the reconnect/resume tests (and
        a chaos lever for drills): senders see a socket error, reconnect
        and resume at the receiver's seq."""
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def receiver(self, name: str, codec: str = "none") -> ChannelReceiver:
        _check_codec(codec, f"receiver channel {name!r}")
        state = self._state_for(name)
        with self._states_lock:
            if state.codec is None:
                state.codec = codec
            elif state.codec != codec:
                raise ValueError(
                    f"channel {name!r} already negotiated codec "
                    f"{state.codec!r}, receiver asked for {codec!r}")
        return ChannelReceiver(self, name, state)

    def _state_for(self, name: str) -> _RecvState:
        with self._states_lock:
            state = self._states.get(name)
            if state is None:
                state = self._states[name] = _RecvState(self.capacity)
            return state

    # -- connection plumbing ------------------------------------------------
    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stopping.is_set():
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            if self._stopping.is_set():
                # accept can still return a queued connection while the
                # listener is being torn down — a handshake served now
                # would let a sender "deliver" into a dead hub
                try:
                    sock.close()
                except OSError:
                    pass
                return
            set_nodelay(sock)
            with self._conns_lock:
                self._conns.add(sock)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             name="tony-channel-conn", daemon=True).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            self._handle_conn(sock)
        finally:
            with self._conns_lock:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _handle_conn(self, sock: socket.socket) -> None:
        try:
            got = recv_exact(sock, len(CH_MAGIC))
        except ProtocolError:
            return
        if got != CH_MAGIC:
            return                          # stray peer: fail at byte 0
        try:
            fr = recv_frame(sock)
            if fr is None or fr[0] != CH_HELLO:
                raise ProtocolError("expected channel HELLO")
            hello = unpack_json(fr[2])
            name = hello.get("channel")
            if not isinstance(name, str) or not name:
                raise ProtocolError(f"bad channel name {name!r}")
            peer_codec = hello.get("codec", "none")
            if peer_codec not in CODECS:
                raise ProtocolError(f"unknown codec {peer_codec!r}")
        except ProtocolError:
            self._best_effort_error(sock, "malformed channel handshake")
            return
        state = self._state_for(name)
        # codec negotiation, BEFORE this connection may preempt the
        # active one: a mismatched dialer is refused channel-scoped
        # (permanent CH_ERROR the sender surfaces as ChannelError) and
        # must not cost the healthy connection its socket
        with self._states_lock:
            if state.codec is None:
                state.codec = peer_codec
            elif state.codec != peer_codec:
                self._best_effort_error(
                    sock, f"codec mismatch: channel {name!r} negotiated "
                          f"{state.codec!r}, sender speaks {peer_codec!r}")
                return
        recv_bytes = self._registry.counter(
            "tony_channel_bytes_total",
            help="logical (decoded) tensor bytes moved", channel=name,
            direction="recv")
        wire_counter = None if state.codec == "none" \
            else self._registry.counter(
                "tony_channel_compressed_bytes_total",
                help="encoded bytes on the wire (codec channels only)",
                channel=name, direction="recv")
        # preempt the predecessor: shutting down its socket makes a
        # half-open connection's blocked read fail NOW, so conn_lock
        # frees instead of this handshake queueing behind a dead peer
        # forever (shutdown, not just close — the delivery thread
        # blocked in recv holds the fd alive, and a bare close() from
        # this thread would not wake it)
        with state.active_lock:
            old, state.active_sock = state.active_sock, sock
        if old is not None and old is not sock:
            try:
                old.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                old.close()
            except OSError:
                pass
        with state.conn_lock:
            with state.active_lock:
                if state.active_sock is not sock:
                    return          # superseded while waiting our turn
            self._deliver(sock, state, recv_bytes, wire_counter)

    def _deliver(self, sock: socket.socket, state: _RecvState,
                 recv_bytes, wire_counter=None) -> None:
        """One connection's delivery loop, serialized per channel by
        ``state.conn_lock`` — the resume value below is only correct
        once no predecessor connection can still advance next_seq."""
        try:
            send_frame(sock, CH_HELLO, 0,
                       pack_json({"v": 1, "resume": state.next_seq}))
        except OSError:
            return
        while not self._stopping.is_set():
            try:
                fr = recv_frame(sock, max_bytes=MAX_TENSOR_BYTES)
            except ProtocolError as e:
                # truncated/garbage frame: channel-SCOPED — this
                # connection dies, the hub keeps serving, the channel
                # state is intact for the sender's resume. The flight
                # recorder dumps a postmortem scoped to THIS connection
                # (healthy channels on the same hub dump nothing).
                self._flight_incident(sock, str(e))
                self._best_effort_error(sock, "malformed tensor frame")
                return
            if fr is None:
                return                      # clean close
            ftype, seq, payload = fr
            if ftype != CH_TENSOR:
                self._best_effort_error(sock, f"unexpected frame {ftype}")
                return
            if seq < state.next_seq:
                # duplicate of something already consumed (resend racing
                # the ack): re-ack so the sender's window advances
                self._best_effort_ack(sock, state.next_seq - 1)
                continue
            if seq > state.next_seq:
                self._best_effort_error(
                    sock, f"seq gap: got {seq}, expected {state.next_seq}")
                return
            try:
                arr = decode_tensor(payload, codec=state.codec or "none")
            except ProtocolError as e:
                self._flight_incident(sock, str(e))
                self._best_effort_error(sock, "undecodable tensor payload")
                return
            if not state.put(arr):
                return                      # hub stopping
            recv_bytes.inc(arr.nbytes)
            if wire_counter is not None:
                wire_counter.inc(len(payload))
            try:
                send_frame(sock, CH_ACK, seq)
            except OSError:
                return

    def _flight_incident(self, sock: socket.socket, error: str) -> None:
        """Torn/garbage channel frame: record + dump the flight ring,
        scoped to the offending connection (its peer address names it).
        Best-effort by the recorder's own contract."""
        from tony_tpu.runtime import tracing
        try:
            peer = str(sock.getpeername())
        except OSError:
            peer = "?"
        flight = tracing.get_flight()
        flight.record("channel_protocol_error", peer=peer,
                      port=self.port, error=error[:500])
        flight.dump("channel_protocol_error", peer=peer)

    @staticmethod
    def _best_effort_error(sock: socket.socket, message: str) -> None:
        try:
            send_frame(sock, CH_ERROR, 0, pack_json({"message": message}))
        except OSError:
            pass

    @staticmethod
    def _best_effort_ack(sock: socket.socket, seq: int) -> None:
        if seq < 0:
            return
        try:
            send_frame(sock, CH_ACK, seq)
        except OSError:
            pass
