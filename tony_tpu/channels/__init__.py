"""Typed inter-gang tensor channels over DCN.

The reusable framework primitive behind cross-slice pipeline training
AND disaggregated prefill/decode serving (prefill gangs ship KV
packages to decode gangs as byte-blob frames —
``ChannelSender.send_bytes`` / ``ChannelReceiver.recv_bytes``):
persistent point-to-point tensor transport between gangs, with bounded
send windows, reconnect-with-seq-resume, and coordinator-owned
endpoint discovery. See ``docs/pipeline.md`` and docs/serving.md
§Disaggregated prefill/decode.
"""

from tony_tpu.channels.channel import (CODECS, ChannelClosed, ChannelError,
                                       ChannelHub, ChannelReceiver,
                                       ChannelSender, decode_tensor,
                                       encode_tensor, forbid_codecs)
from tony_tpu.channels.registry import (ACT_CHANNEL, GRAD_CHANNEL,
                                        StageLinks, act_channel,
                                        build_channel_specs, grad_channel,
                                        open_local_pipeline,
                                        open_stage_links,
                                        open_stage_links_from_env,
                                        parse_channel_spec, stage_env)

__all__ = [
    "CODECS", "ChannelClosed", "ChannelError", "ChannelHub",
    "ChannelReceiver", "ChannelSender",
    "decode_tensor", "encode_tensor", "forbid_codecs",
    "ACT_CHANNEL", "GRAD_CHANNEL", "act_channel", "grad_channel",
    "StageLinks", "build_channel_specs", "open_local_pipeline",
    "open_stage_links", "open_stage_links_from_env", "parse_channel_spec",
    "stage_env",
]
