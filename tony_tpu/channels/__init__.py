"""Typed inter-gang tensor channels over DCN.

The reusable framework primitive behind cross-slice pipeline training
AND disaggregated prefill/decode serving (prefill gangs ship KV
packages to decode gangs as byte-blob frames —
``ChannelSender.send_bytes`` / ``ChannelReceiver.recv_bytes``):
persistent point-to-point tensor transport between gangs, with bounded
send windows, reconnect-with-seq-resume, and coordinator-owned
endpoint discovery. See ``docs/pipeline.md`` and docs/serving.md
§Disaggregated prefill/decode.
"""

from tony_tpu.channels.channel import (ChannelClosed, ChannelError,
                                       ChannelHub, ChannelReceiver,
                                       ChannelSender, decode_tensor,
                                       encode_tensor)
from tony_tpu.channels.registry import (ACT_CHANNEL, GRAD_CHANNEL,
                                        StageLinks, build_channel_specs,
                                        open_local_pipeline,
                                        open_stage_links,
                                        open_stage_links_from_env,
                                        parse_channel_spec, stage_env)

__all__ = [
    "ChannelClosed", "ChannelError", "ChannelHub", "ChannelReceiver",
    "ChannelSender",
    "decode_tensor", "encode_tensor", "ACT_CHANNEL", "GRAD_CHANNEL",
    "StageLinks", "build_channel_specs", "open_local_pipeline",
    "open_stage_links", "open_stage_links_from_env", "parse_channel_spec",
    "stage_env",
]
