"""Coordinator-owned channel registry + trainer-side link discovery.

The coordinator is the only process that sees every gang's registered
endpoint, so IT owns the wiring diagram: at gang-barrier release it
folds the pipeline declaration (``tony.pipeline.stages`` — job types in
stage order) and the per-task channel ports (registered alongside the
data-plane spec) into one per-task **channel spec** shipped back on the
registration response (additive RPC field, the same wire-evolution
precedent as the heartbeat metrics/epoch piggybacks).

Per-task channel spec (JSON on the wire)::

    {"stage": 1, "num_stages": 2, "rank": 0, "ranks": 1,
     "prev": "hostA:chportA",     # stage-1 peer's hub ("" at stage 0)
     "next": "hostC:chportC"}     # stage+1 peer's hub ("" at the last)

Tasks are paired RANK-to-RANK across adjacent stages (rank = position
among the stage job type's participant tasks, index order), which is why
``pipeline_stages()`` validation requires equal instance counts across
stages. The executor turns the spec into ``TONY_PIPELINE_*`` /
``TONY_CHANNEL_*`` env vars; :func:`open_stage_links` turns those back
into live transport objects for the trainer.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tony_tpu import constants
from tony_tpu.channels.channel import ChannelHub, ChannelReceiver, \
    ChannelSender

#: channel names on a task's hub: activations flowing INTO this stage,
#: cotangents flowing back INTO this stage.
ACT_CHANNEL = "act"
GRAD_CHANNEL = "grad"


def build_channel_specs(stages: list[str],
                        tasks_of) -> dict[str, dict]:
    """task_id → channel-spec dict for every task of a pipeline job.

    ``stages``: job types in stage order. ``tasks_of(job_type)`` yields
    that type's participant tasks as ``(task_id, host, channel_port)``
    in index order. A task that registered no channel port (0) gets no
    entry — its stage neighbors' specs then carry "" for that side, and
    the trainer fails fast rather than dialing port 0.
    """
    per_stage: list[list[tuple[str, str, int]]] = [
        list(tasks_of(jt)) for jt in stages]
    specs: dict[str, dict] = {}
    s_count = len(stages)
    for k, members in enumerate(per_stage):
        for rank, (task_id, host, port) in enumerate(members):
            def _peer(stage_members, r):
                if not stage_members or r >= len(stage_members):
                    return ""
                _, h, p = stage_members[r]
                return f"{h}:{p}" if p else ""
            specs[task_id] = {
                "stage": k,
                "num_stages": s_count,
                "rank": rank,
                "ranks": len(members),
                "prev": _peer(per_stage[k - 1], rank) if k > 0 else "",
                "next": _peer(per_stage[k + 1], rank)
                        if k < s_count - 1 else "",
            }
    return specs


# ---------------------------------------------------------------------------
# Trainer side
# ---------------------------------------------------------------------------
@dataclass
class StageLinks:
    """A stage gang member's live transport endpoints, as consumed by
    :class:`tony_tpu.parallel.pipeline.CrossSlicePipeline`:

    - ``act_in`` / ``grad_in``: receivers on this task's own hub
      (activations from stage-1, cotangents from stage+1)
    - ``act_out`` / ``grad_out``: senders dialing the neighbors' hubs

    Boundary stages hold ``None`` on the missing side. ``close`` drains
    senders (so the last microbatch's grads land) then stops the hub.
    """
    stage: int
    num_stages: int
    rank: int = 0
    hub: ChannelHub | None = None
    act_in: ChannelReceiver | None = None
    act_out: ChannelSender | None = None
    grad_in: ChannelReceiver | None = None
    grad_out: ChannelSender | None = None

    @property
    def is_first(self) -> bool:
        return self.stage == 0

    @property
    def is_last(self) -> bool:
        return self.stage == self.num_stages - 1

    def close(self) -> None:
        for sender in (self.act_out, self.grad_out):
            if sender is not None:
                sender.close(drain=True)
        if self.hub is not None:
            self.hub.stop()


def open_stage_links(*, stage: int, num_stages: int, rank: int = 0,
                     prev: str = "", next: str = "",
                     hub_port: int = 0, window: int = 8,
                     capacity: int = 8, registry=None) -> StageLinks:
    """Stand up this task's hub and dial its neighbors. ``prev``/``next``
    are the neighbor hubs' ``host:port`` endpoints ("" at the pipeline
    boundary). Senders dial lazily — a neighbor whose hub is still
    coming up is absorbed by the sender's connect retry."""
    if not 0 <= stage < num_stages:
        raise ValueError(f"stage {stage} outside 0..{num_stages - 1}")
    if stage > 0 and not prev:
        raise ValueError(f"stage {stage} has no upstream channel endpoint")
    if stage < num_stages - 1 and not next:
        raise ValueError(f"stage {stage} has no downstream channel endpoint")
    hub = ChannelHub(port=hub_port, capacity=capacity, registry=registry)
    hub.start()
    links = StageLinks(stage=stage, num_stages=num_stages, rank=rank,
                       hub=hub)
    if stage > 0:
        links.act_in = hub.receiver(ACT_CHANNEL)
        links.grad_out = ChannelSender(prev, GRAD_CHANNEL, window=window,
                                       registry=registry)
    if stage < num_stages - 1:
        links.grad_in = hub.receiver(GRAD_CHANNEL)
        links.act_out = ChannelSender(next, ACT_CHANNEL, window=window,
                                      registry=registry)
    return links


def open_local_pipeline(num_stages: int, *, window: int = 8,
                        capacity: int = 8, registry=None,
                        endpoint_map=None) -> list[StageLinks]:
    """Wire ``num_stages`` in-process stages over loopback — the bench
    and test harness for the cross-slice schedule (each "gang" is a
    thread). All hubs start first, so there is no bring-up ordering
    problem; ``endpoint_map(stage, port) -> "host:port"`` lets a harness
    interpose a latency proxy in front of any stage's hub."""
    hubs = [ChannelHub(capacity=capacity, registry=registry)
            for _ in range(num_stages)]
    ports = [hub.start() for hub in hubs]

    def addr(k: int) -> str:
        if endpoint_map is not None:
            return endpoint_map(k, ports[k])
        return f"127.0.0.1:{ports[k]}"

    links = []
    for k in range(num_stages):
        link = StageLinks(stage=k, num_stages=num_stages, hub=hubs[k])
        if k > 0:
            link.act_in = hubs[k].receiver(ACT_CHANNEL)
            link.grad_out = ChannelSender(addr(k - 1), GRAD_CHANNEL,
                                          window=window, registry=registry)
        if k < num_stages - 1:
            link.grad_in = hubs[k].receiver(GRAD_CHANNEL)
            link.act_out = ChannelSender(addr(k + 1), ACT_CHANNEL,
                                         window=window, registry=registry)
        links.append(link)
    return links


def stage_env(environ=None) -> dict | None:
    """Parse the executor-exported pipeline env (None when this process
    is not a pipeline stage)."""
    env = os.environ if environ is None else environ
    stage = env.get(constants.PIPELINE_STAGE)
    if stage is None or stage == "":
        return None
    return {
        "stage": int(stage),
        "num_stages": int(env.get(constants.PIPELINE_NUM_STAGES, "1")),
        "rank": int(env.get(constants.PIPELINE_RANK, "0")),
        "prev": env.get(constants.CHANNEL_PREV, ""),
        "next": env.get(constants.CHANNEL_NEXT, ""),
        "hub_port": int(env.get(constants.CHANNEL_PORT, "0")),
    }


def open_stage_links_from_env(environ=None, *, window: int = 8,
                              capacity: int = 8,
                              registry=None) -> StageLinks | None:
    """One-call trainer bootstrap: env → live :class:`StageLinks`.
    The hub binds the port the EXECUTOR reserved and advertised to the
    coordinator — peers are already dialing it."""
    env = stage_env(environ)
    if env is None:
        return None
    return open_stage_links(window=window, capacity=capacity,
                            registry=registry, **env)


def parse_channel_spec(spec_json: str) -> dict | None:
    """Decode the wire channel spec; None for non-pipeline workers
    (empty string) or malformed payloads (fail soft: the trainer then
    simply is not a pipeline stage)."""
    if not spec_json:
        return None
    try:
        obj = json.loads(spec_json)
    except json.JSONDecodeError:
        return None
    return obj if isinstance(obj, dict) and "stage" in obj else None
