"""Coordinator-owned channel registry + trainer-side link discovery.

The coordinator is the only process that sees every gang's registered
endpoint, so IT owns the wiring diagram: at gang-barrier release it
folds the pipeline declaration (``tony.pipeline.stages`` — job types in
stage order) and the per-task channel ports (registered alongside the
data-plane spec) into one per-task **channel spec** shipped back on the
registration response (additive RPC field, the same wire-evolution
precedent as the heartbeat metrics/epoch piggybacks).

Per-task channel spec (JSON on the wire)::

    {"stage": 1, "num_stages": 2, "rank": 0, "ranks": 1,
     "prev": "hostA:chportA",     # stage-1 peer's hub ("" at stage 0)
     "next": "hostC:chportC"}     # stage+1 peer's hub ("" at the last)

Tasks are paired RANK-to-RANK across adjacent stages (rank = position
among the stage job type's participant tasks, index order), which is why
``pipeline_stages()`` validation requires equal instance counts across
stages. The executor turns the spec into ``TONY_PIPELINE_*`` /
``TONY_CHANNEL_*`` env vars; :func:`open_stage_links` turns those back
into live transport objects for the trainer.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tony_tpu import constants
from tony_tpu.channels.channel import CODECS, ChannelHub, ChannelReceiver, \
    ChannelSender

#: channel names on a task's hub: activations flowing INTO this stage,
#: cotangents flowing back INTO this stage. With interleaving (v
#: virtual stage chunks per gang) each chunk gets its own lane —
#: ``act.1``, ``grad.2``, … — named by the CONSUMING chunk; chunk 0
#: keeps the bare names, so a non-interleaved job's wire is unchanged.
ACT_CHANNEL = "act"
GRAD_CHANNEL = "grad"


def act_channel(chunk: int = 0) -> str:
    return ACT_CHANNEL if chunk == 0 else f"{ACT_CHANNEL}.{chunk}"


def grad_channel(chunk: int = 0) -> str:
    return GRAD_CHANNEL if chunk == 0 else f"{GRAD_CHANNEL}.{chunk}"


def build_channel_specs(stages: list[str], tasks_of, *,
                        interleave: int = 1,
                        compression: str = "none") -> dict[str, dict]:
    """task_id → channel-spec dict for every task of a pipeline job.

    ``stages``: job types in stage order. ``tasks_of(job_type)`` yields
    that type's participant tasks as ``(task_id, host, channel_port)``
    in index order. A task that registered no channel port (0) gets no
    entry — its stage neighbors' specs then carry "" for that side, and
    the trainer fails fast rather than dialing port 0.

    ``interleave`` > 1 (tony.pipeline.interleave) gives every gang that
    many VIRTUAL stages and closes the stage chain into a ring: the last
    gang's ``next`` becomes gang 0's hub (activations wrapping into the
    next chunk) and gang 0's ``prev`` the last gang's (cotangents
    wrapping back). ``compression`` (tony.channel.compression) rides
    every spec so each gang opens its channels with the same codec.
    Both fields are ADDITIVE on the wire: defaults are omitted, so old
    executors parse new specs and vice versa.
    """
    per_stage: list[list[tuple[str, str, int]]] = [
        list(tasks_of(jt)) for jt in stages]
    specs: dict[str, dict] = {}
    s_count = len(stages)
    for k, members in enumerate(per_stage):
        for rank, (task_id, host, port) in enumerate(members):
            def _peer(stage_members, r):
                if not stage_members or r >= len(stage_members):
                    return ""
                _, h, p = stage_members[r]
                return f"{h}:{p}" if p else ""
            ring = interleave > 1
            prev = _peer(per_stage[k - 1], rank) \
                if (k > 0 or ring) else ""      # k-1 = -1 wraps the ring
            nxt = _peer(per_stage[(k + 1) % s_count], rank) \
                if (k < s_count - 1 or ring) else ""
            specs[task_id] = {
                "stage": k,
                "num_stages": s_count,
                "rank": rank,
                "ranks": len(members),
                "prev": prev,
                "next": nxt,
            }
            if interleave > 1:
                specs[task_id]["interleave"] = interleave
            if compression != "none":
                specs[task_id]["compression"] = compression
    return specs


# ---------------------------------------------------------------------------
# Trainer side
# ---------------------------------------------------------------------------
@dataclass
class StageLinks:
    """A stage gang member's live transport endpoints, as consumed by
    :class:`tony_tpu.parallel.pipeline.CrossSlicePipeline`:

    - ``act_in`` / ``grad_in``: receivers on this task's own hub
      (activations from stage-1, cotangents from stage+1)
    - ``act_out`` / ``grad_out``: senders dialing the neighbors' hubs

    Boundary stages hold ``None`` on the missing side. ``close`` drains
    senders (so the last microbatch's grads land) then stops the hub.

    With ``interleave`` = v > 1 the gang holds v virtual stage CHUNKS
    (global virtual stage of chunk j = ``j * num_stages + stage``, the
    Megatron looping placement) and the per-chunk lanes live in the
    ``act_ins`` / ``act_outs`` / ``grad_ins`` / ``grad_outs`` lists
    (index = chunk, ``None`` at the model boundary); the scalar fields
    stay chunk 0's lanes for the non-interleaved consumers. Stage
    neighbors form a RING: every chunk's activations go out on ``next``
    and cotangents on ``prev``, the lane NAME carrying the consuming
    chunk (``act``, ``act.1``, …).
    """
    stage: int
    num_stages: int
    rank: int = 0
    interleave: int = 1
    compression: str = "none"
    hub: ChannelHub | None = None
    act_in: ChannelReceiver | None = None
    act_out: ChannelSender | None = None
    grad_in: ChannelReceiver | None = None
    grad_out: ChannelSender | None = None
    act_ins: list = None
    act_outs: list = None
    grad_ins: list = None
    grad_outs: list = None

    @property
    def is_first(self) -> bool:
        return self.stage == 0

    @property
    def is_last(self) -> bool:
        return self.stage == self.num_stages - 1

    @property
    def num_virtual(self) -> int:
        return self.num_stages * self.interleave

    def global_stage(self, chunk: int = 0) -> int:
        """This gang's chunk ``chunk`` as a VIRTUAL stage index in
        0..num_virtual-1 (looping placement)."""
        return chunk * self.num_stages + self.stage

    def _senders(self):
        seen = []
        for group in (self.act_outs or [self.act_out],
                      self.grad_outs or [self.grad_out]):
            for sender in group:
                if sender is not None and sender not in seen:
                    seen.append(sender)
        return seen

    def close(self) -> None:
        for sender in self._senders():
            sender.close(drain=True)
        if self.hub is not None:
            self.hub.stop()


def _wire_links(links: StageLinks, *, prev: str, next: str,
                window: int, registry) -> StageLinks:
    """Attach the per-chunk lanes (and the chunk-0 scalar mirrors) to a
    StageLinks whose hub is already listening. The only topology rule:
    chunk j of gang s is virtual stage g = j*S + s; activations for g+1
    ride ``next`` (the ring successor gang) on the CONSUMING chunk's act
    lane, cotangents for g-1 ride ``prev`` on the consuming chunk's grad
    lane. For interleave=1 this reduces exactly to the historical
    act/grad pair."""
    s, S, v = links.stage, links.num_stages, links.interleave
    V = links.num_virtual
    codec = links.compression
    hub = links.hub
    links.act_ins, links.act_outs = [], []
    links.grad_ins, links.grad_outs = [], []
    for j in range(v):
        g = j * S + s
        links.act_ins.append(
            hub.receiver(act_channel(j), codec=codec) if g > 0 else None)
        links.grad_ins.append(
            hub.receiver(grad_channel(j), codec=codec)
            if g < V - 1 else None)
        # consuming chunk on the ring successor/predecessor gang:
        # same chunk when the hop stays inside the chain, next/previous
        # chunk when it wraps past gang S-1 / gang 0
        links.act_outs.append(
            ChannelSender(next, act_channel(j if s < S - 1 else j + 1),
                          window=window, codec=codec, registry=registry)
            if g < V - 1 else None)
        links.grad_outs.append(
            ChannelSender(prev, grad_channel(j if s > 0 else j - 1),
                          window=window, codec=codec, registry=registry)
            if g > 0 else None)
    links.act_in = links.act_ins[0]
    links.act_out = links.act_outs[0]
    links.grad_in = links.grad_ins[0]
    links.grad_out = links.grad_outs[0]
    return links


def open_stage_links(*, stage: int, num_stages: int, rank: int = 0,
                     prev: str = "", next: str = "",
                     interleave: int = 1, compression: str = "none",
                     hub_port: int = 0, window: int = 8,
                     capacity: int = 8, registry=None) -> StageLinks:
    """Stand up this task's hub and dial its neighbors. ``prev``/``next``
    are the neighbor hubs' ``host:port`` endpoints ("" at the pipeline
    boundary; with ``interleave`` > 1 the boundary gangs need them too —
    the stages close into a ring). Senders dial lazily — a neighbor
    whose hub is still coming up is absorbed by the sender's connect
    retry."""
    if not 0 <= stage < num_stages:
        raise ValueError(f"stage {stage} outside 0..{num_stages - 1}")
    if interleave < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    ring = interleave > 1
    if (stage > 0 or ring) and not prev:
        raise ValueError(f"stage {stage} has no upstream channel endpoint")
    if (stage < num_stages - 1 or ring) and not next:
        raise ValueError(f"stage {stage} has no downstream channel endpoint")
    hub = ChannelHub(port=hub_port, capacity=capacity, registry=registry)
    hub.start()
    links = StageLinks(stage=stage, num_stages=num_stages, rank=rank,
                       interleave=interleave, compression=compression,
                       hub=hub)
    return _wire_links(links, prev=prev, next=next, window=window,
                       registry=registry)


def open_local_pipeline(num_stages: int, *, window: int = 8,
                        capacity: int = 8, interleave: int = 1,
                        compression: str = "none", registry=None,
                        endpoint_map=None) -> list[StageLinks]:
    """Wire ``num_stages`` in-process stages over loopback — the bench
    and test harness for the cross-slice schedule (each "gang" is a
    thread). All hubs start first, so there is no bring-up ordering
    problem; ``endpoint_map(stage, port) -> "host:port"`` lets a harness
    interpose a latency proxy in front of any stage's hub."""
    hubs = [ChannelHub(capacity=capacity, registry=registry)
            for _ in range(num_stages)]
    ports = [hub.start() for hub in hubs]
    ring = interleave > 1

    def addr(k: int) -> str:
        if endpoint_map is not None:
            return endpoint_map(k, ports[k])
        return f"127.0.0.1:{ports[k]}"

    links = []
    for k in range(num_stages):
        link = StageLinks(stage=k, num_stages=num_stages,
                          interleave=interleave, compression=compression,
                          hub=hubs[k])
        prev = addr((k - 1) % num_stages) if (k > 0 or ring) else ""
        nxt = addr((k + 1) % num_stages) \
            if (k < num_stages - 1 or ring) else ""
        links.append(_wire_links(link, prev=prev, next=nxt,
                                 window=window, registry=registry))
    return links


def stage_env(environ=None) -> dict | None:
    """Parse the executor-exported pipeline env (None when this process
    is not a pipeline stage)."""
    env = os.environ if environ is None else environ
    stage = env.get(constants.PIPELINE_STAGE)
    if stage is None or stage == "":
        return None
    return {
        "stage": int(stage),
        "num_stages": int(env.get(constants.PIPELINE_NUM_STAGES, "1")),
        "rank": int(env.get(constants.PIPELINE_RANK, "0")),
        "prev": env.get(constants.CHANNEL_PREV, ""),
        "next": env.get(constants.CHANNEL_NEXT, ""),
        "interleave": int(env.get(constants.PIPELINE_INTERLEAVE, "1")
                          or "1"),
        "compression": env.get(constants.CHANNEL_COMPRESSION, "") or "none",
        "hub_port": int(env.get(constants.CHANNEL_PORT, "0")),
    }


def open_stage_links_from_env(environ=None, *, window: int = 8,
                              capacity: int = 8,
                              registry=None) -> StageLinks | None:
    """One-call trainer bootstrap: env → live :class:`StageLinks`.
    The hub binds the port the EXECUTOR reserved and advertised to the
    coordinator — peers are already dialing it."""
    env = stage_env(environ)
    if env is None:
        return None
    return open_stage_links(window=window, capacity=capacity,
                            registry=registry, **env)


def parse_channel_spec(spec_json: str) -> dict | None:
    """Decode the wire channel spec; None for non-pipeline workers
    (empty string) or malformed payloads (fail soft: the trainer then
    simply is not a pipeline stage)."""
    if not spec_json:
        return None
    try:
        obj = json.loads(spec_json)
    except json.JSONDecodeError:
        return None
    return obj if isinstance(obj, dict) and "stage" in obj else None
