"""Logical-axis sharding rules: names in models, meshes at runtime.

Green-field for the TPU build (the reference delegates all parallelism to the
user script — SURVEY.md §2.3). Models annotate arrays with *logical* axis
names ("batch", "embed", "heads", ...); a rule table maps those to mesh axes.
Swapping DP→FSDP→TP+SP is then a rule-table change, not a model change —
the same decoupling the scaling-book recipe prescribes: pick a mesh, annotate
shardings, let XLA insert the collectives.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[tuple[str, str | tuple[str, ...] | None]]

# Default rule table for transformer-family models. First matching rule wins;
# a mesh axis not present in the mesh resolves to replication.
DEFAULT_RULES: Rules = (
    ("batch", ("dp", "fsdp")),       # batch over dp and fsdp jointly
    ("seq", "cp"),                   # context parallelism: sequence split
    ("embed", "fsdp"),               # FSDP shards params on the embed dim
    ("heads", "tp"),                 # attention heads over tensor axis
    ("kv", None),                    # per-head dim: never sharded
    ("mlp", "tp"),                   # MLP hidden over tensor axis
    ("vocab", "tp"),                 # embedding/logits vocab over tensor axis
    ("expert", "ep"),                # MoE experts over expert axis
    ("stage", "pp"),                 # pipeline stages
    ("norm", None),
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_rep(x, axis_name: str):
    """``lax.psum`` whose TRANSPOSE treats the cotangent as replicated.

    Inside a ``shard_map`` body with ``check_vma=False``, the stock
    psum's transpose psums the cotangent again — a replicated seed (the
    usual case: a loss differentiated identically on every rank) comes
    back multiplied by the axis size, so a per-rank ``jax.vjp`` of a
    cross-shard reduction yields axis_size x the true partials
    (measured: seeding 1.0 through a tp=2 psum doubles every upstream
    gradient). This wrapper's backward is the identity, so per-rank
    vjps yield TRUE partials — callers then sum partials across the
    axis exactly once, where they choose to (the 1F1B pipeline's
    head_reduce_axes does). Use for manual-collective loss heads; the
    primal is a plain psum."""
    return jax.lax.psum(x, axis_name)


def _psum_rep_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _psum_rep_bwd(axis_name, _res, ct):
    return (ct,)


psum_rep.defvjp(_psum_rep_fwd, _psum_rep_bwd)


def _auto_axes(mesh) -> set[str]:
    """Mesh axes that sharding constraints may refer to. Inside ``shard_map``
    the ambient AbstractMesh marks its axes Manual and
    ``with_sharding_constraint`` rejects specs naming them — the collective
    layout there is the shard_map's business, so :func:`constrain` must
    resolve those axes to replication (e.g. model code reused as a pipeline
    stage body — parallel/pipeline.py runs blocks under shard_map)."""
    types = getattr(mesh, "axis_types", None)
    if types is None:
        return set(mesh.shape)
    return {name for name, t in zip(mesh.axis_names, types)
            if "Manual" not in str(t)}


def _resolve(logical: str | None, rules: Rules, mesh: Mesh,
             used: set[str], auto: set[str]):
    if logical is None:
        return None
    for name, target in rules:
        if name == logical:
            if target is None:
                return None
            targets = (target,) if isinstance(target, str) else tuple(target)
            # a mesh axis may shard at most one array dim: earlier dims win
            # (e.g. ("batch","embed") on a pure-fsdp mesh → batch gets fsdp,
            # embed replicates instead of raising DuplicateSpecError)
            live = tuple(t for t in targets
                         if t in mesh.shape and mesh.shape[t] > 1
                         and t not in used and t in auto)
            if not live:
                return None
            used.update(live)
            return live if len(live) > 1 else live[0]
    return None


def logical_to_spec(logical_axes: Sequence[str | None], mesh: Mesh,
                    rules: Rules = DEFAULT_RULES) -> P:
    """("batch", "embed") → PartitionSpec(("dp","fsdp"), "fsdp") under rules,
    dropping mesh axes that don't exist, have size 1, are already used by
    an earlier dim of the same array, or are Manual (inside shard_map)."""
    used: set[str] = set()
    auto = _auto_axes(mesh)
    return P(*(_resolve(ax, rules, mesh, used, auto) for ax in logical_axes))


def logical_sharding(logical_axes: Sequence[str | None], mesh: Mesh,
                     rules: Rules = DEFAULT_RULES) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, mesh, rules))


def shard_pytree(tree: Any, logical_tree: Any, mesh: Mesh,
                 rules: Rules = DEFAULT_RULES) -> Any:
    """Device-put every leaf of ``tree`` per its logical axes in
    ``logical_tree`` (same structure, leaves are tuples of axis names)."""
    return jax.tree.map(
        lambda x, ax: jax.device_put(x, logical_sharding(ax, mesh, rules)),
        tree, logical_tree, is_leaf=lambda x: x is None)


def constrain(x, logical_axes: Sequence[str | None], mesh: Mesh | None = None,
              rules: Rules = DEFAULT_RULES):
    """``with_sharding_constraint`` by logical names. With no explicit mesh,
    the ambient mesh context (``jax.sharding.set_mesh`` / trace-time abstract
    mesh) is used; a no-op when neither exists (single-device, plain tests)."""
    if mesh is not None:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, logical_to_spec(logical_axes, mesh, rules)))
    ambient = jax.sharding.get_abstract_mesh()
    if ambient is None or ambient.empty:
        return x
    return jax.lax.with_sharding_constraint(
        x, logical_to_spec(logical_axes, ambient, rules))


def _is_axes_leaf(x: Any) -> bool:
    """A leaf of a logical-axes pytree: None, or a tuple of axis names/None.
    Distinguishes the axes tuple ("stage","embed") from structural tuples
    like a ((W_axes, b_axes), ...) params container."""
    return x is None or (isinstance(x, tuple) and all(
        e is None or isinstance(e, str) for e in x))


def param_shardings(logical_tree: Any, mesh: Mesh,
                    rules: Rules = DEFAULT_RULES) -> Any:
    """Map a logical-axes pytree → NamedSharding pytree (for jit in_shardings/
    out_shardings)."""
    return jax.tree.map(
        lambda ax: logical_sharding(ax, mesh, rules),
        logical_tree, is_leaf=_is_axes_leaf)


def attention_spec(mesh: Mesh, batch_axes, seq_axis: str | None,
                   head_axis: str | None):
    """PartitionSpec for [B, S, H, D] attention operands under shard_map:
    batch over the live subset of ``batch_axes``, sequence over ``seq_axis``,
    heads over ``head_axis``; axes missing from the mesh (or size 1) are
    dropped. Returns (spec, seq_axis_live: str | None) — shared by the
    context-parallel attention wrappers (ring / ulysses)."""
    from jax.sharding import PartitionSpec as P
    live = lambda a: a is not None and a in mesh.shape and mesh.shape[a] > 1
    b_spec = tuple(a for a in batch_axes if live(a)) or None
    if isinstance(b_spec, tuple) and len(b_spec) == 1:
        b_spec = b_spec[0]
    s_spec = seq_axis if live(seq_axis) else None
    h_spec = head_axis if live(head_axis) else None
    return P(b_spec, s_spec, h_spec, None), s_spec
