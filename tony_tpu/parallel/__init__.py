"""Parallelism layer: mesh presets, sharding rules, and SPMD strategies.

First-class in the TPU build (the reference delegates parallelism entirely to
user TF/PT code — SURVEY.md §2.3): DP/FSDP/TP/SP as sharding rules over a
global mesh, CP as ring attention, PP as a GPipe shard_map schedule, EP as
gshard dense dispatch. All collectives are XLA-inserted (pjit) or explicit
ppermute/psum (shard_map) riding ICI/DCN.
"""

from tony_tpu.parallel.mesh import (
    AXIS_ORDER,
    PRESETS,
    make_mesh,
    parse_mesh_string,
)
from tony_tpu.parallel.moe import MoEMetrics, default_capacity, moe_ffn
from tony_tpu.parallel.pipeline import pipeline_apply
from tony_tpu.parallel.ring_attention import (
    ring_attention,
    ring_attention_local,
)
from tony_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_attention_local,
)
from tony_tpu.parallel.sharding import (
    DEFAULT_RULES,
    constrain,
    logical_sharding,
    logical_to_spec,
    param_shardings,
    shard_pytree,
)

__all__ = [
    "AXIS_ORDER",
    "DEFAULT_RULES",
    "MoEMetrics",
    "PRESETS",
    "constrain",
    "default_capacity",
    "logical_sharding",
    "logical_to_spec",
    "make_mesh",
    "moe_ffn",
    "param_shardings",
    "parse_mesh_string",
    "pipeline_apply",
    "ring_attention",
    "ring_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
    "shard_pytree",
]
