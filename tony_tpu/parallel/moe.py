"""Expert parallelism: gshard-style mixture-of-experts dispatch.

Green-field for the TPU build (SURVEY.md §2.3: EP absent from the reference).
TPU-first design: dispatch/combine are *dense einsums* against a capacity-
bounded one-hot routing tensor, with experts sharded over the mesh's ``ep``
axis via logical-axis constraints — XLA then lowers the resharding to
all_to_all collectives over ICI. No per-token gather/scatter loops (which
would defeat MXU tiling and force dynamic shapes).

Static shapes everywhere: each expert processes a fixed ``capacity`` of
tokens; overflow tokens are dropped (their combine weight is zero), the
standard gshard/switch trade for compile-time-known shapes.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from tony_tpu.parallel.sharding import constrain


class MoEMetrics(NamedTuple):
    """Router health numbers (load-balance aux loss per Switch-Transformer)."""
    aux_loss: jax.Array        # scalar: E * sum(frac_tokens * frac_probs)
    dropped_fraction: jax.Array


def router_dispatch(logits: jax.Array, num_experts: int, *, top_k: int = 2,
                    capacity: int):
    """Top-k routing with capacity. logits: [B, S, E].

    Returns (dispatch [B,S,E,C] one-hot, combine [B,S,E,C] weights, metrics).
    """
    b, s, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, top_k)          # [B,S,k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)  # [B,S,k,E]
    # token-major priority: earlier sequence positions win capacity slots
    oh = onehot.reshape(b, s * top_k, e)
    pos = jnp.cumsum(oh, axis=1) - oh                       # slot within expert
    keep = (pos < capacity).astype(jnp.float32) * oh        # [B,S*k,E]
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32) * keep[..., None]
    combine = pos_oh * gate_vals.reshape(b, s * top_k, 1, 1)
    dispatch = pos_oh.reshape(b, s, top_k, e, capacity).sum(2)
    combine = combine.reshape(b, s, top_k, e, capacity).sum(2)

    # Switch-Transformer load-balance loss: E * Σ_e f_e * p_e
    frac_tokens = onehot[:, :, 0, :].mean(axis=(0, 1))      # top-1 assignment
    frac_probs = probs.mean(axis=(0, 1))
    aux = e * jnp.sum(frac_tokens * frac_probs)
    routed = keep.sum() / jnp.maximum(oh.sum(), 1.0)
    return dispatch, combine, MoEMetrics(aux, 1.0 - routed)


def default_capacity(tokens_per_group: int, num_experts: int, top_k: int,
                     capacity_factor: float = 1.25) -> int:
    c = int(math.ceil(top_k * tokens_per_group / num_experts
                      * capacity_factor))
    return max(c, 1)


def moe_ffn(x: jax.Array, router_w: jax.Array, w_in: jax.Array,
            w_out: jax.Array, *, top_k: int = 2,
            capacity_factor: float = 1.25,
            activation=jax.nn.gelu) -> tuple[jax.Array, MoEMetrics]:
    """Mixture-of-experts feed-forward block.

    x: [B, S, D]; router_w: [D, E]; w_in: [E, D, H]; w_out: [E, H, D].
    Experts carry logical axis "expert" → mesh ``ep``; the two big einsums
    below keep data in [E, B, C, D] layout so the ep resharding is a single
    all_to_all on entry and exit.
    """
    b, s, d = x.shape
    e = router_w.shape[1]
    capacity = default_capacity(s, e, top_k, capacity_factor)

    logits = jnp.einsum("bsd,de->bse", x, router_w,
                        preferred_element_type=jnp.float32)
    dispatch, combine, metrics = router_dispatch(
        logits, e, top_k=top_k, capacity=capacity)

    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    # [B,S,E,C] × [B,S,D] → [E,B,C,D]: the all_to_all boundary (ep enters)
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)
    expert_in = constrain(expert_in, ("expert", "batch", None, "embed"))
    h = activation(jnp.einsum("ebcd,edh->ebch", expert_in, w_in))
    h = constrain(h, ("expert", "batch", None, "mlp"))
    expert_out = jnp.einsum("ebch,ehd->ebcd", h, w_out)
    # [B,S,E,C] × [E,B,C,D] → [B,S,D]: ep exits (second all_to_all)
    out = jnp.einsum("bsec,ebcd->bsd", combine, expert_out)
    out = constrain(out, ("batch", "seq", "embed"))
    return out, metrics


def moe_ffn_manual(x: jax.Array, router_w: jax.Array, w_in_local: jax.Array,
                   w_out_local: jax.Array, *, axis_name: str,
                   num_experts: int, top_k: int = 2,
                   capacity_factor: float = 1.25,
                   activation=jax.nn.gelu) -> tuple[jax.Array, MoEMetrics]:
    """Expert-parallel MoE with EXPLICIT collectives — the arm for Manual
    (``shard_map``) contexts, where :func:`moe_ffn`'s sharding constraints
    can't reach the ``ep`` axis. This is what lets MoE compose with
    pipeline parallelism: the GPipe stage body runs under shard_map, so
    the dispatch must speak the bound axis name directly.

    Layout: activations are REPLICATED along ``axis_name`` (the pipeline
    shards its microbatch over dp only); each rank holds
    ``num_experts / ep`` experts' weights (``w_in_local`` leads with the
    local expert count). Routing is computed identically on every rank
    from the replicated activations, each rank slices its experts'
    dispatch/combine columns, runs its experts, and the partial combines
    ``psum`` into the full output — one collective per block. Gradients
    flow through slice + psum by plain AD (the transposed collective is
    the identity broadcast).
    """
    b, s, d = x.shape
    e = num_experts
    e_loc = w_in_local.shape[0]
    capacity = default_capacity(s, e, top_k, capacity_factor)

    logits = jnp.einsum("bsd,de->bse", x, router_w,
                        preferred_element_type=jnp.float32)
    dispatch, combine, metrics = router_dispatch(
        logits, e, top_k=top_k, capacity=capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    rank = lax.axis_index(axis_name)
    d_loc = lax.dynamic_slice_in_dim(dispatch, rank * e_loc, e_loc, axis=2)
    c_loc = lax.dynamic_slice_in_dim(combine, rank * e_loc, e_loc, axis=2)
    expert_in = jnp.einsum("bsec,bsd->ebcd", d_loc, x)
    h = activation(jnp.einsum("ebcd,edh->ebch", expert_in, w_in_local))
    expert_out = jnp.einsum("ebch,ehd->ebcd", h, w_out_local)
    out = lax.psum(jnp.einsum("bsec,ebcd->bsd", c_loc, expert_out),
                   axis_name)
    return out, metrics
