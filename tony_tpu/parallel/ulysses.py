"""Ulysses sequence parallelism: all-to-all context parallelism over ``cp``.

The second of the two long-context strategies SURVEY.md calls for ("ring
attention or all-to-all sequence/context parallelism" — the reference has
neither, §2.3). Where ring attention keeps the sequence sharded and rotates
K/V around the ring (cp × ppermute hops, O(S/cp) memory), Ulysses
re-shards: one all-to-all converts sequence-sharded [B, S/c, H, D] into
head-sharded [B, S, H/c, D], attention runs over the FULL sequence with
H/c local heads (so the un-sharded flash kernel applies directly), and a
second all-to-all restores sequence sharding.

Trade-offs vs ring (why both exist):
- Ulysses: 2 all-to-alls total (bandwidth-optimal on switched/ICI tori for
  moderate cp), full-sequence attention per device → head-count must be
  divisible by cp, memory O(S) per device for the attention inputs.
- Ring: cp neighbor hops, O(S/cp) memory, no head-divisibility constraint —
  the choice for extreme sequence lengths.

Same call shape as :func:`tony_tpu.parallel.ring_attention.ring_attention`.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
from jax import lax
from jax.sharding import Mesh

from tony_tpu.parallel.ring_attention import _single_chunk


def ulysses_attention_local(q, k, v, *, axis_name: str = "cp",
                            causal: bool = True,
                            scale: float | None = None):
    """Per-shard Ulysses body — call inside ``shard_map`` with the sequence
    dim sharded over ``axis_name``.

    q: [B, S_local, H, D]; k/v: [B, S_local, H_kv, D] with H_kv | H —
    grouped-query K/V ride the all-to-all UNEXPANDED when H_kv divides
    the axis size: the kv-head dim splits over cp exactly like the query
    heads, and the contiguous split preserves group alignment (each cp
    rank's H/cp query heads are exactly (H_kv/cp)·(H/H_kv), so query
    head j still pairs with local kv head j // rep). The K/V payload —
    the strategy's whole inter-chip cost besides q/o — shrinks by
    H/H_kv. H and H_kv must both divide the axis size (the wrapper
    expands K/V first when H_kv cannot).
    Returns [B, S_local, H, D].
    """
    from tony_tpu.parallel.ring_attention import _flash_block, _flash_chunks

    b, s_loc, h, d = q.shape
    h_kv = k.shape[2]
    cp = lax.axis_size(axis_name)
    if h % cp:
        raise ValueError(f"n_heads={h} not divisible by {axis_name}={cp}")
    if h_kv % cp:
        raise ValueError(f"kv heads ({h_kv}) not divisible by "
                         f"{axis_name}={cp}; expand K/V first "
                         f"(ulysses_attention does this automatically)")
    if _flash_chunks() and _flash_block(s_loc * cp) is None:
        # Unlike ring chunks (S_local each), ulysses attends the FULL
        # gathered sequence per device — a silent dense fallback there
        # would materialize the O(S²) score tensor the strategy exists to
        # avoid. Fail with the remedy instead.
        raise ValueError(
            f"ulysses full sequence {s_loc * cp} does not tile any flash "
            f"block; pad the per-device full sequence to a multiple of "
            f"128 on TPU (8 in interpret mode)")
    if cp == 1:
        return _single_chunk(q, k, v, causal=causal, scale=scale)

    def seq_to_heads(x):
        # [B, S/c, H', D] → [B, S, H'/c, D]: split heads across the axis,
        # gather the full sequence (H' = H for q, H_kv for k/v).
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # full sequence is local after the all-to-all; _single_chunk picks the
    # engine (flash pallas kernel on TPU with a tiling block, dense
    # otherwise) — one selection policy shared with the ring path; both
    # consume grouped K/V natively
    o = _single_chunk(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(o)


def ulysses_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                      scale: float | None = None,
                      batch_axes: Sequence[str] = ("dp", "fsdp"),
                      seq_axis: str = "cp", head_axis: str = "tp"):
    """Sequence-parallel attention over global [B, S, H, D] arrays — the
    all-to-all counterpart of :func:`ring_attention` (same call shape).
    Batch over dp/fsdp, sequence over cp, heads over tp; axes missing from
    ``mesh`` (or size 1) are dropped. With tp live, each tp shard runs
    Ulysses over its own head subset (local heads must still divide cp).

    GQA K/V (fewer heads than Q) ride the all-to-alls UNEXPANDED when the
    kv heads divide both the tp sharding and the cp split — the K/V
    payload shrinks by H/H_kv, the same discipline as the ring's
    unexpanded rotation. Otherwise (H_kv < tp·cp granularity) K/V expand
    to full width first — correctness over the payload saving."""
    import jax.numpy as jnp

    from tony_tpu.parallel.sharding import attention_spec
    spec, s_spec = attention_spec(mesh, batch_axes, seq_axis, head_axis)
    h, hk = q.shape[2], k.shape[2]
    if hk != h and (hk <= 0 or h % hk):
        raise ValueError(f"kv heads ({hk}) must divide heads ({h})")
    if hk != h:
        tp = mesh.shape.get(head_axis, 1) if head_axis else 1
        cp = mesh.shape.get(seq_axis, 1) if seq_axis else 1
        # the kv-head dim must survive the tp shard AND the local
        # all-to-all split: hk % (tp·cp) == 0 keeps every rank's local
        # kv heads aligned with its query-head groups
        if hk % max(tp, 1) or (hk // max(tp, 1)) % max(cp, 1):
            rep = h // hk
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

    if s_spec is None:
        fn = functools.partial(_single_chunk, causal=causal, scale=scale)
    else:
        fn = functools.partial(ulysses_attention_local, axis_name=seq_axis,
                               causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
