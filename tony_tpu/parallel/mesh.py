"""Device-mesh construction and named presets.

The reference has no parallelism layer of its own — it delegates to TF/PT in
user code and only ships host:port lists (SURVEY.md §2.3; reference:
TonySession.getClusterSpec:227). On TPU the mesh IS the parallelism contract:
every strategy (DP/FSDP/TP/SP/CP/PP/EP) is an axis of one global
``jax.sharding.Mesh``, and XLA inserts the collectives (psum/all-gather/
reduce-scatter/ppermute) that ride ICI within a slice and DCN across slices.
This module is therefore a first-class component of the TPU build even though
it has no direct reference analog.

Canonical axis names (used by sharding rules, models, and ops):

    dp    data parallelism (batch split, gradient psum)
    fsdp  fully-sharded data parallelism (batch + param shard, same axis)
    tp    tensor parallelism (feature/heads split inside a layer)
    sp    sequence parallelism for norms/activations (reuses tp axis groups)
    cp    context parallelism (sequence split for ring attention)
    pp    pipeline parallelism (layer stages)
    ep    expert parallelism (MoE expert split)
"""

from __future__ import annotations

import math

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "cp", "ep", "tp")
"""Canonical major→minor ordering. Minor-most axes get neighboring devices
(fastest ICI links), so tp — the most latency-sensitive collective group —
is last; pp — the least chatty (point-to-point activations only) — is first
so stages may even span DCN."""


def make_mesh(axes: dict[str, int] | None = None,
              devices=None,
              axis_order: tuple[str, ...] | None = None):
    """Build a ``jax.sharding.Mesh`` over all global devices.

    ``axes`` maps axis name → size; at most one size may be -1/0 (inferred
    from the device count). Axes of size 1 are kept, so sharding rules that
    name them still resolve. Empty/None axes yields ``{"dp": n}``.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices() if devices is None else devices)
    n = devs.size
    axes = dict(axes or {})
    if not axes:
        axes = {"dp": n}
    unknown = [k for k, v in axes.items() if v in (-1, 0)]
    known = math.prod(v for v in axes.values() if v not in (-1, 0))
    if len(unknown) == 1:
        if n % known:
            raise ValueError(f"cannot infer {unknown[0]}: {n} devices not "
                             f"divisible by {known}")
        axes[unknown[0]] = n // known
    elif len(unknown) > 1:
        raise ValueError(f"at most one inferred (-1) mesh axis: {axes}")
    total = math.prod(axes.values())
    if total != n:
        raise ValueError(f"mesh axes {axes} require {total} devices, have {n}")
    if axis_order is None:
        # canonical order first, then any custom axes in declaration order
        names = tuple(a for a in AXIS_ORDER if a in axes)
        names += tuple(a for a in axes if a not in names)
    else:
        names = tuple(axis_order)
    shape = tuple(axes[name] for name in names)
    return Mesh(devs.reshape(shape), names)


def parse_mesh_string(spec: str) -> dict[str, int]:
    """Parse the ``tony.application.mesh`` config value: "dp=2,tp=4" →
    {"dp": 2, "tp": 4}. "-1" sizes are allowed (inferred at mesh build)."""
    axes: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, size = part.partition("=")
        if not size:
            raise ValueError(f"malformed mesh axis {part!r} in {spec!r}")
        axes[name.strip()] = int(size)
    return axes


# ---------------------------------------------------------------------------
# Presets: the strategies the task brief requires as first-class citizens.
# Each returns an axes dict for make_mesh; -1 folds the remaining devices in.
# ---------------------------------------------------------------------------

def preset_dp() -> dict[str, int]:
    """Pure data parallelism — every chip holds full params."""
    return {"dp": -1}


def preset_fsdp() -> dict[str, int]:
    """Fully-sharded DP: batch and params sharded over one axis."""
    return {"fsdp": -1}


def preset_dp_tp(tp: int) -> dict[str, int]:
    """2D: batch over dp, layer internals over tp (minor axis → ICI)."""
    return {"dp": -1, "tp": tp}


def preset_fsdp_tp(tp: int) -> dict[str, int]:
    return {"fsdp": -1, "tp": tp}


def preset_long_context(cp: int, tp: int = 1) -> dict[str, int]:
    """Long-context: sequence over cp (ring attention), internals over tp."""
    return {"dp": -1, "cp": cp, "tp": tp}


def preset_pipeline(pp: int, tp: int = 1) -> dict[str, int]:
    return {"pp": pp, "dp": -1, "tp": tp}


def preset_moe(ep: int, tp: int = 1) -> dict[str, int]:
    """Expert parallelism: experts over ep, dense internals over tp."""
    return {"dp": -1, "ep": ep, "tp": tp}


PRESETS = {
    "dp": preset_dp,
    "fsdp": preset_fsdp,
    "dp_tp": preset_dp_tp,
    "fsdp_tp": preset_fsdp_tp,
    "long_context": preset_long_context,
    "pipeline": preset_pipeline,
    "moe": preset_moe,
}
