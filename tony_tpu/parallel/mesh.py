"""Device-mesh construction and named presets.

The reference has no parallelism layer of its own — it delegates to TF/PT in
user code and only ships host:port lists (SURVEY.md §2.3; reference:
TonySession.getClusterSpec:227). On TPU the mesh IS the parallelism contract:
every strategy (DP/FSDP/TP/SP/CP/PP/EP) is an axis of one global
``jax.sharding.Mesh``, and XLA inserts the collectives (psum/all-gather/
reduce-scatter/ppermute) that ride ICI within a slice and DCN across slices.
This module is therefore a first-class component of the TPU build even though
it has no direct reference analog.

Canonical axis names (used by sharding rules, models, and ops):

    dp    data parallelism (batch split, gradient psum)
    fsdp  fully-sharded data parallelism (batch + param shard, same axis)
    tp    tensor parallelism (feature/heads split inside a layer)
    sp    sequence parallelism for norms/activations (reuses tp axis groups)
    cp    context parallelism (sequence split for ring attention)
    pp    pipeline parallelism (layer stages)
    ep    expert parallelism (MoE expert split)
"""

from __future__ import annotations

import math

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "cp", "ep", "tp")
"""Canonical major→minor ordering. Minor-most axes get neighboring devices
(fastest ICI links), so tp — the most latency-sensitive collective group —
is last; pp — the least chatty (point-to-point activations only) — is first
so stages may even span DCN."""


def make_mesh(axes: dict[str, int] | None = None,
              devices=None,
              axis_order: tuple[str, ...] | None = None):
    """Build a ``jax.sharding.Mesh`` over all global devices.

    ``axes`` maps axis name → size; at most one size may be -1/0 (inferred
    from the device count). Axes of size 1 are kept, so sharding rules that
    name them still resolve. Empty/None axes yields ``{"dp": n}``.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices() if devices is None else devices)
    n = devs.size
    axes = dict(axes or {})
    if not axes:
        axes = {"dp": n}
    unknown = [k for k, v in axes.items() if v in (-1, 0)]
    known = math.prod(v for v in axes.values() if v not in (-1, 0))
    if len(unknown) == 1:
        if n % known:
            raise ValueError(f"cannot infer {unknown[0]}: {n} devices not "
                             f"divisible by {known}")
        axes[unknown[0]] = n // known
    elif len(unknown) > 1:
        raise ValueError(f"at most one inferred (-1) mesh axis: {axes}")
    total = math.prod(axes.values())
    if total != n:
        raise ValueError(f"mesh axes {axes} require {total} devices, have {n}")
    if axis_order is None:
        # canonical order first, then any custom axes in declaration order
        names = tuple(a for a in AXIS_ORDER if a in axes)
        names += tuple(a for a in axes if a not in names)
    else:
        names = tuple(axis_order)
    shape = tuple(axes[name] for name in names)
    return Mesh(devs.reshape(shape), names)


def make_hybrid_mesh(ici_axes: dict[str, int], dcn_axes: dict[str, int],
                     devices=None):
    """Build a mesh spanning multiple pod slices: ``dcn_axes`` are laid out
    ACROSS slices (data-center network — slow, so keep them to low-traffic
    collectives like DP gradient reduction), ``ici_axes`` within each slice.

    Uses ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` when the
    devices carry real slice indices (TPU multi-slice). On backends without
    ``slice_index`` (the 8-device virtual CPU mesh used in tests and the
    driver dryrun) it falls back to a contiguous reshape: the session
    assigns dense process ids in slice-major order (cluster/session.py), so
    contiguous device ranges ARE slices and the reshape places dcn axes
    major / ici axes minor exactly like the real thing.

    ``-1`` inference is supported on at most one ICI axis (the per-slice
    device count divides it); dcn axes must be explicit — their product is
    the slice count.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(jax.devices() if devices is None else devices)
    n = len(devs)
    dcn_axes = {k: v for k, v in dcn_axes.items()}
    if not dcn_axes:
        return make_mesh(ici_axes, devices=devs)
    num_slices = math.prod(dcn_axes.values())
    if any(v in (-1, 0) for v in dcn_axes.values()):
        raise ValueError(f"dcn axes must be explicit (no -1): {dcn_axes}")
    if n % num_slices:
        raise ValueError(f"{n} devices do not split into {num_slices} "
                         f"slices (dcn axes {dcn_axes})")
    per_slice = n // num_slices
    ici_axes = dict(ici_axes or {})
    if not ici_axes:
        # default axis name must not collide with a dcn axis ("dp" across
        # slices + unset tony.application.mesh is the documented common case)
        name = next(a for a in ("dp", "fsdp", "ici") if a not in dcn_axes)
        ici_axes = {name: per_slice}
    unknown = [k for k, v in ici_axes.items() if v in (-1, 0)]
    known = math.prod(v for v in ici_axes.values() if v not in (-1, 0))
    if len(unknown) == 1:
        if per_slice % known:
            raise ValueError(f"cannot infer {unknown[0]}: {per_slice} "
                             f"per-slice devices not divisible by {known}")
        ici_axes[unknown[0]] = per_slice // known
    elif len(unknown) > 1:
        raise ValueError(f"at most one inferred (-1) ici axis: {ici_axes}")
    if math.prod(ici_axes.values()) != per_slice:
        raise ValueError(f"ici axes {ici_axes} require "
                         f"{math.prod(ici_axes.values())} devices per "
                         f"slice, have {per_slice}")

    # dcn major, then ici axes in canonical order
    dcn_names = tuple(a for a in AXIS_ORDER if a in dcn_axes)
    dcn_names += tuple(a for a in dcn_axes if a not in dcn_names)
    ici_names = tuple(a for a in AXIS_ORDER if a in ici_axes)
    ici_names += tuple(a for a in ici_axes if a not in ici_names)
    overlap = set(dcn_names) & set(ici_names)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} appear in both the ici "
                         f"and dcn layouts")
    names = dcn_names + ici_names

    if all(getattr(d, "slice_index", None) is not None for d in devs) \
            and len({getattr(d, "slice_index") for d in devs}) > 1:
        from jax.experimental import mesh_utils
        # create_hybrid_device_mesh multiplies the two shapes elementwise,
        # so pad with 1s to keep dcn axes (major) disjoint from ici axes
        mesh_arr = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_names) + tuple(ici_axes[a] for a in ici_names),
            tuple(dcn_axes[a] for a in dcn_names) + (1,) * len(ici_names),
            devices=devs)
        return Mesh(mesh_arr, names)
    shape = tuple(dcn_axes[a] for a in dcn_names) + \
        tuple(ici_axes[a] for a in ici_names)
    return Mesh(np.array(devs).reshape(shape), names)


def parse_mesh_string(spec: str) -> dict[str, int]:
    """Parse the ``tony.application.mesh`` config value: "dp=2,tp=4" →
    {"dp": 2, "tp": 4}. "-1" sizes are allowed (inferred at mesh build)."""
    axes: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, size = part.partition("=")
        if not size:
            raise ValueError(f"malformed mesh axis {part!r} in {spec!r}")
        axes[name.strip()] = int(size)
    return axes


# ---------------------------------------------------------------------------
# Presets: the strategies the task brief requires as first-class citizens.
# Each returns an axes dict for make_mesh; -1 folds the remaining devices in.
# ---------------------------------------------------------------------------

def preset_dp() -> dict[str, int]:
    """Pure data parallelism — every chip holds full params."""
    return {"dp": -1}


def preset_fsdp() -> dict[str, int]:
    """Fully-sharded DP: batch and params sharded over one axis."""
    return {"fsdp": -1}


def preset_dp_tp(tp: int) -> dict[str, int]:
    """2D: batch over dp, layer internals over tp (minor axis → ICI)."""
    return {"dp": -1, "tp": tp}


def preset_fsdp_tp(tp: int) -> dict[str, int]:
    return {"fsdp": -1, "tp": tp}


def preset_long_context(cp: int, tp: int = 1) -> dict[str, int]:
    """Long-context: sequence over cp (ring attention), internals over tp."""
    return {"dp": -1, "cp": cp, "tp": tp}


def preset_pipeline(pp: int, tp: int = 1) -> dict[str, int]:
    return {"pp": pp, "dp": -1, "tp": tp}


def preset_moe(ep: int, tp: int = 1) -> dict[str, int]:
    """Expert parallelism: experts over ep, dense internals over tp."""
    return {"dp": -1, "ep": ep, "tp": tp}


PRESETS = {
    "dp": preset_dp,
    "fsdp": preset_fsdp,
    "dp_tp": preset_dp_tp,
    "fsdp_tp": preset_fsdp_tp,
    "long_context": preset_long_context,
    "pipeline": preset_pipeline,
    "moe": preset_moe,
}
