"""Pipeline parallelism: in-slice GPipe/1F1B over ``pp``, and
cross-slice MPMD 1F1B over DCN tensor channels.

Green-field for the TPU build (SURVEY.md §2.3: PP absent from the reference).
Stages live on different devices along the mesh's ``pp`` axis; activations
hop stage→stage with ``lax.ppermute`` (point-to-point, so pp tolerates DCN).

Two schedules:

* **GPipe** (:func:`pipeline_apply`, the default): all-forward-then-backward.
  With S stages and M microbatches the loop runs M+S-1 ticks, bubble
  (S-1)/(M+S-1). Differentiable end-to-end — AD through scan+ppermute yields
  the reverse schedule automatically — which is what makes it drop into any
  ``jax.grad`` without ceremony. The cost is activation memory: the scan
  holds every tick's stage input for the backward, O(M) microbatch
  activations per device.

* **1F1B** (:func:`pipeline_value_and_grad`): each stage starts microbatch
  backwards as soon as the last stage has consumed that microbatch, so at
  most S microbatch activations are ever live per device — O(S) instead of
  O(M), the schedule that lets deep pipelines scale M for bubble without
  scaling memory. The price of starting backwards early is that the loss
  must be computed per microbatch INSIDE the pipeline (at the last stage),
  so this entry point takes the loss head and returns gradients explicitly
  rather than being differentiated through. Backward ticks recompute the
  stage forward from the saved input (one extra forward per microbatch —
  the same trade a remat'd GPipe stage makes).

* **Cross-slice 1F1B** (:class:`CrossSlicePipeline`): the MPMD variant —
  each STAGE runs in its own gang (its own process set, its own slice's
  ICI domain), with activations and cotangents hopping between gangs
  over the typed DCN tensor channels (``tony_tpu.channels``) instead of
  ``lax.ppermute``. The host drives the same non-interleaved 1F1B
  schedule per stage; channel send/recv threads keep microbatch m±1's
  transport in flight while microbatch m computes on the devices — the
  same overlap discipline as the DevicePrefetcher. This is what trains
  models that don't fit one slice's ICI domain.

Constraint (both schedules): the stage function must map activations to
activations of the same shape/dtype (natural for transformer blocks).
Per-stage params are stacked on a leading [S, ...] axis, sharded P("pp") —
each device reads only its own stage's slice.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _axis_size(axis_name: str):
    """lax.axis_size across jax versions (0.4.x predates it): the size
    of a named mesh axis from inside a shard_map body."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map across jax versions: the public alias (with
    ``check_vma``) landed after 0.4.x, where the same entry point lives
    in jax.experimental.shard_map with the flag named ``check_rep``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def _pipeline_local(stage_params: Any, microbatches: jax.Array, *,
                    stage_fn: Callable[[Any, jax.Array], Any],
                    axis_name: str, with_aux: bool,
                    batch_axes: tuple[str, ...]) -> Any:
    """Per-device pipeline body (inside shard_map over ``axis_name``).

    stage_params: this stage's params (leading [1, ...] shard dim squeezed).
    microbatches: [M, mb, ...] — replicated input; stage 0 consumes it.
    Returns [M, mb, ...] final-stage outputs, replicated via psum; with
    ``with_aux`` the stage_fn returns (out, scalar) and the scalar is
    accumulated over VALID ticks only (warmup/drain ticks run the stage on
    garbage state whose aux must not count), summed over stages, and
    averaged over the batch axes.
    """
    s = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda x: x[0], stage_params)
    m = microbatches.shape[0]
    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    aux0 = jnp.zeros((), jnp.float32)
    shift = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        # stage 0 ingests microbatch t while t < M; later stages use the
        # activation that arrived from the previous stage last tick
        inp = jnp.where(stage == 0, microbatches[jnp.minimum(t, m - 1)], state)
        res = stage_fn(params, inp)
        out, aux = res if with_aux else (res, aux0)
        # stage s processes microbatch t-s at tick t; anything else is
        # pipeline bubble running on zeros/garbage
        valid = jnp.logical_and(t - stage >= 0, t - stage < m)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # the final stage finishes microbatch t-(S-1) at tick t
        widx = t - (s - 1)
        take = jnp.logical_and(stage == s - 1, widx >= 0)
        slot = jnp.clip(widx, 0, m - 1)
        outputs = outputs.at[slot].set(
            jnp.where(take, out, outputs[slot]))
        state = lax.ppermute(out, axis_name, shift)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = lax.scan(
        tick, (state, outputs, aux0), jnp.arange(m + s - 1, dtype=jnp.int32))
    # only the last stage holds real outputs; broadcast around the ring so
    # the result is replicated over pp (out_spec P() below)
    mask = (stage == s - 1).astype(outputs.dtype)
    outputs = lax.psum(outputs * mask, axis_name)
    if not with_aux:
        return outputs
    # stages sum (each holds different layers), microbatches average (the
    # /m outside), batch shards average — replicated on every device
    aux_acc = lax.psum(aux_acc, axis_name)
    for a in batch_axes:
        aux_acc = lax.pmean(aux_acc, a)
    return outputs, aux_acc


def _pipeline_1f1b_local(stage_params: Any, head_params: Any,
                         microbatches: jax.Array, head_batches: Any, *,
                         stage_fn: Callable[[Any, jax.Array], jax.Array],
                         loss_head: Callable[[Any, jax.Array, Any],
                                             jax.Array],
                         axis_name: str,
                         batch_axes: tuple[str, ...],
                         head_specs: Any = None,
                         stage_specs: Any = None,
                         head_reduce_axes: tuple[str, ...] = (),
                         with_aux: bool = False,
                         aux_weight: float = 0.0) -> tuple:
    """Per-device 1F1B body (inside shard_map over ``axis_name``).

    The Megatron non-interleaved schedule in closed form — for stage s of
    S with warmup w(s) = S-1-s, microbatch i runs::

        forward  at tick s + i          (i < w: pipeline warmup)
                 at tick 2i + s         (steady 1F1B cadence)
        backward at tick 2S - 1 - s + 2i

    over T = 2M + 2S - 2 ticks. Forward ticks save ONLY the stage input
    into a depth-S ring (in-flight microbatches per stage never exceed
    S - s); backward ticks re-run the stage forward under ``jax.vjp`` from
    that input (remat-style) and produce param grads plus the input
    cotangent. The last stage seeds its backward from ``loss_head``
    directly — no output cotangent ever enters from outside, which is
    precisely what lets backwards start before the full batch has been
    forwarded. Activations hop forward and cotangents hop backward via
    ppermute OUTSIDE the scheduling conds (collectives must execute
    uniformly on every device every tick; unscheduled devices ship
    zeros that are never read — the closed forms above guarantee a
    consumer tick always directly follows a producer tick).

    Returns (loss_sum, stage_grads, head_grads, dxs) — per-device, not
    yet reduced: loss_sum/head_grads live on the last stage, dxs on stage
    0, stage_grads on their own stage.
    """
    s_count = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda v: v[0], stage_params)
    m = microbatches.shape[0]
    n_ticks = 2 * m + 2 * s_count - 2

    def fwd_sched(sg, t):
        """(does stage ``sg`` forward at tick ``t``, which microbatch)."""
        w = s_count - 1 - sg
        ts = t - sg
        has = (ts >= 0) & (
            (ts < jnp.minimum(w, m))
            | ((ts % 2 == 0) & (ts // 2 >= w) & (ts // 2 < m)))
        idx = jnp.clip(jnp.where(ts < w, ts, ts // 2), 0, m - 1)
        return has, idx

    carry0 = (
        jnp.zeros_like(microbatches[0]),                 # fwd_state (wire)
        jnp.zeros_like(microbatches[0]),                 # cot_state (wire)
        jnp.zeros((s_count,) + microbatches.shape[1:],
                  microbatches.dtype),                   # in_buf ring
        jnp.zeros((s_count,) + microbatches.shape[1:],
                  microbatches.dtype),                   # resid ring
        jnp.zeros_like(microbatches),                    # dxs (stage 0)
        jax.tree.map(jnp.zeros_like, params),            # stage grads
        jax.tree.map(jnp.zeros_like, head_params),       # head grads
        jnp.zeros((), jnp.float32),                      # loss sum
    )

    def tick(carry, t):
        (fwd_state, cot_state, in_buf, resid, dxs, grads, hgrads,
         loss_acc) = carry
        has_fwd, fwd_i = fwd_sched(stage, t)
        u = t - (2 * s_count - 1 - stage)
        has_bwd = (u >= 0) & (u % 2 == 0) & (u // 2 < m)
        bwd_i = jnp.clip(u // 2, 0, m - 1)

        # file the arrival: the activation on the wire was sent by the
        # previous stage's forward LAST tick; at warmup->steady boundaries
        # its consumption tick here lags the arrival by more than one
        # tick, so a bare register would be overwritten by later sends —
        # the ring holds each microbatch until this stage's schedule
        # reaches it (in-flight never exceeds S, same bound as resid)
        has_in, in_i = fwd_sched((stage - 1) % s_count, t - 1)
        has_in = has_in & (t >= 1)
        in_buf = in_buf.at[in_i % s_count].set(
            jnp.where(has_in, fwd_state, in_buf[in_i % s_count]))

        inp = jnp.where(stage == 0, microbatches[fwd_i],
                        in_buf[fwd_i % s_count])

        def fwd_branch(resid):
            out = stage_fn(params, inp)
            if with_aux:
                out = out[0]        # aux re-derived in the backward tick
            return out, resid.at[fwd_i % s_count].set(inp)

        def fwd_noop(resid):
            return jnp.zeros_like(fwd_state), resid

        out, resid = lax.cond(has_fwd, fwd_branch, fwd_noop, resid)

        saved = resid[bwd_i % s_count]
        head_mb = jax.tree.map(lambda a: a[bwd_i], head_batches)

        def bwd_branch(op):
            grads, hgrads, dxs, loss_acc = op

            def last_case(_):
                # aux-path gradients are REPLICATED across the head's
                # reduce axes (every rank computes the full aux), while
                # CE-path gradients are per-rank partials (psum_rep in
                # the loss head) — the reductions below psum BOTH, so
                # the aux seed pre-divides by the reduce-axes product to
                # come out exact; the reported loss re-applies the true
                # weight via the vjp's aux output
                denom = 1
                for _ax in head_reduce_axes:
                    denom = denom * _axis_size(_ax)
                w_eff = aux_weight / denom

                def last_fn(p, hp, x):
                    res = stage_fn(p, x)
                    if with_aux:
                        out, aux = res
                        return (loss_head(hp, out, head_mb)
                                + w_eff * aux), aux
                    return loss_head(hp, res, head_mb), jnp.zeros(())
                lval, vjp_fn, aux_v = jax.vjp(last_fn, params, head_params,
                                              saved, has_aux=True)
                lval = lval + (aux_weight - w_eff) * aux_v.astype(
                    lval.dtype)
                dp, dhp, dinp = vjp_fn(jnp.ones((), lval.dtype))
                # head sharded over head_reduce_axes (tp-vocab shards):
                # each rank's vjp yields the PARTIAL cotangents from its
                # vocab slice's loss paths — the activation cotangent and
                # any head leaf replicated over those axes must sum
                # across them (sharded leaves own their slice's grads
                # outright). Safe inside the conds: the predicates are
                # uniform across the reduce axes, so all participants
                # enter together.
                def _reduce_tree(grads_tree, specs_tree, ax):
                    """psum ``grads_tree`` leaves over ``ax`` EXCEPT those
                    whose spec already shards over ``ax`` (they own their
                    slice's grads outright). Specs are zipped by hand: P
                    is a tuple subclass tree.map would descend into, and
                    a bare None leaf (valid replicated spec) vanishes
                    from tree_leaves without the is_leaf."""
                    flat_g, td = jax.tree_util.tree_flatten(grads_tree)
                    flat_s = jax.tree_util.tree_leaves(
                        specs_tree,
                        is_leaf=lambda x: x is None or isinstance(x, P))

                    def _reduce(g, spec):
                        named = set()
                        for entry in (tuple(spec) if spec is not None
                                      else ()):
                            if isinstance(entry, (tuple, list)):
                                named.update(entry)
                            elif entry is not None:
                                named.add(entry)
                        return g if ax in named else lax.psum(g, ax)

                    return td.unflatten(
                        [_reduce(g, s) for g, s in zip(flat_g, flat_s)])

                for ax in head_reduce_axes:
                    dinp = lax.psum(dinp, ax)
                    # the last STAGE's params also sit upstream of the
                    # partitioned loss paths — their partials sum too,
                    # spec-aware like the head's (an ax-sharded stage
                    # leaf owns its slice)
                    dp = _reduce_tree(dp, stage_specs, ax)
                    dhp = _reduce_tree(dhp, head_specs, ax)
                return dp, dhp, dinp, lval.astype(jnp.float32)

            def mid_case(_):
                if with_aux:
                    (out2, aux_v), vjp_fn = jax.vjp(
                        lambda p, x: stage_fn(p, x), params, saved)
                    # seed the aux cotangent with its loss weight: one
                    # vjp covers both the activation path and the
                    # stage-local aux-loss path
                    dp, dinp = vjp_fn(
                        (cot_state, jnp.asarray(aux_weight, aux_v.dtype)))
                    lval = (aux_weight * aux_v).astype(jnp.float32)
                else:
                    out2, vjp_fn = jax.vjp(
                        lambda p, x: stage_fn(p, x), params, saved)
                    dp, dinp = vjp_fn(cot_state)
                    lval = jnp.zeros((), jnp.float32)
                return (dp, jax.tree.map(jnp.zeros_like, head_params),
                        dinp, lval)

            dp, dhp, dinp, lval = lax.cond(stage == s_count - 1,
                                           last_case, mid_case, None)
            grads = jax.tree.map(jnp.add, grads, dp)
            hgrads = jax.tree.map(jnp.add, hgrads, dhp)
            # dxs is only meaningful on stage 0 (masked at the end)
            dxs = dxs.at[bwd_i].set(
                jnp.where(stage == 0, dinp, dxs[bwd_i]))
            return (grads, hgrads, dxs, loss_acc + lval), dinp

        def bwd_noop(op):
            return op, jnp.zeros_like(cot_state)

        (grads, hgrads, dxs, loss_acc), dinp = lax.cond(
            has_bwd, bwd_branch, bwd_noop, (grads, hgrads, dxs, loss_acc))

        shift_f = [(i, (i + 1) % s_count) for i in range(s_count)]
        shift_b = [(i, (i - 1) % s_count) for i in range(s_count)]
        fwd_state = lax.ppermute(out, axis_name, shift_f)
        cot_state = lax.ppermute(dinp, axis_name, shift_b)
        return (fwd_state, cot_state, in_buf, resid, dxs, grads, hgrads,
                loss_acc), None

    carry, _ = lax.scan(tick, carry0,
                        jnp.arange(n_ticks, dtype=jnp.int32))
    _, _, _, _, dxs, grads, hgrads, loss_acc = carry

    last = (stage == s_count - 1)
    # every stage contributes to loss_acc (mid stages their weighted aux,
    # the last stage CE + aux) — plain psum over pp sums them exactly once
    loss = lax.psum(loss_acc, axis_name) / m
    hgrads = jax.tree.map(
        lambda g: lax.psum(jnp.where(last, g, jnp.zeros_like(g)),
                           axis_name), hgrads)
    first = (stage == 0)
    dxs = jax.tree.map(
        lambda g: lax.psum(jnp.where(first, g, jnp.zeros_like(g)),
                           axis_name), dxs)
    # reduce over the data axes: params (and the head) are replicated
    # across dp/fsdp, so their grads average; loss averages; dx is the
    # cotangent of THIS shard's tokens — scaled, not summed
    d_total = 1
    for a in batch_axes:
        d_total *= _axis_size(a)
        loss = lax.pmean(loss, a)
        grads = jax.tree.map(lambda g, _a=a: lax.pmean(g, _a), grads)
        hgrads = jax.tree.map(lambda g, _a=a: lax.pmean(g, _a), hgrads)
    grads = jax.tree.map(lambda g: g[None] / m, grads)
    hgrads = jax.tree.map(lambda g: g / m, hgrads)
    dxs = dxs / (m * d_total)
    return loss, grads, hgrads, dxs


def pipeline_value_and_grad(stage_fn: Callable[[Any, jax.Array], jax.Array],
                            stacked_params: Any, x: jax.Array,
                            head_params: Any, head_batch: Any, mesh: Mesh,
                            *, loss_head: Callable[[Any, jax.Array, Any],
                                                   jax.Array],
                            num_microbatches: int, axis_name: str = "pp",
                            batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                            param_specs: Any = None,
                            head_specs: Any = None,
                            head_reduce_axes: tuple[str, ...] = (),
                            with_aux: bool = False,
                            aux_weight: float = 0.0):
    """1F1B pipeline: loss AND gradients in one schedule.

    Same stage contract as :func:`pipeline_apply` (stacked [S, ...]
    params, shape-preserving ``stage_fn``), plus the loss head the last
    stage applies per microbatch: ``loss_head(head_params, out_mb,
    head_batch_mb) -> scalar`` — the mean loss of the LOCAL microbatch
    shard (so the global loss is exactly the mean of per-microbatch means;
    with masked losses this matches a single global mean only when every
    microbatch shard has the same mask count — the standard 1F1B
    normalization trade).

    ``head_batch``: pytree with leading batch dim [B, ...] (targets etc.),
    microbatched and delivered to ``loss_head`` alongside the activations.

    Returns ``(loss, stage_grads, head_grads, dx)`` where stage_grads
    matches ``stacked_params``, head_grads matches ``head_params``, and
    dx is d(loss)/dx — feed it to the caller's vjp of whatever produced x
    (the embedding) to complete the parameter gradients.

    Activation memory is O(S) microbatches per device (vs GPipe's O(M));
    each microbatch pays one extra stage forward (remat-style recompute in
    the backward tick). Not differentiable through — it IS the
    differentiation.

    ``head_specs`` / ``head_reduce_axes``: by default head_params (and
    their gradients) replicate on every device (in_specs P()) — the
    loss head runs inside the shard_map's Manual context, where GSPMD
    sharding constraints cannot reach. To SHARD the head (a big lm_head
    over tp), pass per-leaf ``head_specs`` and name the sharding axes in
    ``head_reduce_axes``; ``loss_head`` must then combine across those
    axes itself (distributed logsumexp etc. — psum/pmax over the axis
    names), and the pipeline psums the activation cotangent plus any
    still-replicated head leaves' grads across them (sharded leaves own
    their slice's grads). transformer.lm_value_and_grad wires this up
    for the vocab-sharded lm_head.
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible into "
                         f"{num_microbatches} microbatches")
    num_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    m = num_microbatches
    mb = b // m
    xs = x.reshape((m, mb) + x.shape[1:])
    head_xs = jax.tree.map(
        lambda a: a.reshape((m, mb) + a.shape[1:]), head_batch)

    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        # degenerate: no pp axis — same value/grad contract via plain AD
        def total(sp, hp, xs):
            def body(h, p):
                if with_aux:
                    out, aux = stage_fn(p, h)
                    return out, aux
                return stage_fn(p, h), None

            def one_mb(xmb, hmb):
                out, auxes = lax.scan(body, xmb, sp)
                loss = loss_head(hp, out, hmb)
                if with_aux:
                    loss = loss + aux_weight * auxes.sum()
                return loss

            losses = jax.vmap(one_mb)(xs, head_xs)
            return losses.mean()

        (loss, (g_sp, g_hp, g_xs)) = jax.value_and_grad(
            total, argnums=(0, 1, 2))(stacked_params, head_params, xs)
        return loss, g_sp, g_hp, g_xs.reshape(x.shape)

    pp = mesh.shape[axis_name]
    if num_stages != pp:
        raise ValueError(f"{num_stages} stacked stages but pp axis has "
                         f"{pp} ranks — need exactly one stage per rank")
    live = tuple(a for a in batch_axes
                 if a in mesh.shape and mesh.shape[a] > 1)
    data_spec = P(None, live if len(live) > 1 else (live[0] if live else None))
    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    if head_specs is None:
        head_specs = jax.tree.map(lambda _: P(), head_params)
    fn = functools.partial(_pipeline_1f1b_local, stage_fn=stage_fn,
                           loss_head=loss_head, axis_name=axis_name,
                           batch_axes=live, head_specs=head_specs,
                           stage_specs=param_specs,
                           head_reduce_axes=head_reduce_axes,
                           with_aux=with_aux, aux_weight=aux_weight)
    loss, g_sp, g_hp, g_xs = _shard_map(
        fn, mesh=mesh,
        in_specs=(param_specs, head_specs, data_spec, data_spec),
        out_specs=(P(), param_specs, head_specs, data_spec),
        check_vma=False)(stacked_params, head_params, xs, head_xs)
    return loss, g_sp, g_hp, g_xs.reshape(x.shape)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], Any],
                   stacked_params: Any, x: jax.Array, mesh: Mesh, *,
                   num_microbatches: int, axis_name: str = "pp",
                   batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                   with_aux: bool = False,
                   param_specs: Any = None):
    """Run x through S pipeline stages of ``stage_fn``.

    stacked_params: pytree whose leaves lead with the stage axis [S, ...];
    S must equal the ``pp`` mesh axis size (one stage per pp rank).
    x: [B, ...] global batch; must divide into ``num_microbatches``; the
    microbatch dim stays sharded over the live batch axes (dp/fsdp).
    Returns [B, ...] outputs (replicated over pp).

    ``with_aux``: stage_fn returns (out, scalar); the scalars from valid
    (non-bubble) ticks sum over stages and average over microbatches and
    batch shards — the MoE load-balance loss channel; returns (out, aux).
    ``param_specs``: override the default P(pp) per-leaf placement — how
    MoE expert weights additionally shard over ``ep`` inside the stage
    (leaves then arrive in the body already sliced to the rank's experts).
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible into "
                         f"{num_microbatches} microbatches")
    num_stages = jax.tree.leaves(stacked_params)[0].shape[0]

    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        # degenerate: no pp axis — run stages sequentially via scan
        if with_aux:
            def body_aux(carry, p):
                h, acc = carry
                h, aux = stage_fn(p, h)
                return (h, acc + aux), None
            (out, aux), _ = lax.scan(
                body_aux, (x, jnp.zeros((), jnp.float32)), stacked_params)
            return out, aux

        def body(h, p):
            return stage_fn(p, h), None
        out, _ = lax.scan(body, x, stacked_params)
        return out

    pp = mesh.shape[axis_name]
    if num_stages != pp:
        raise ValueError(f"{num_stages} stacked stages but pp axis has "
                         f"{pp} ranks — need exactly one stage per rank")
    mb = b // num_microbatches
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])

    live = tuple(a for a in batch_axes
                 if a in mesh.shape and mesh.shape[a] > 1)
    data_spec = P(None, live if len(live) > 1 else (live[0] if live else None))
    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = functools.partial(_pipeline_local, stage_fn=stage_fn,
                           axis_name=axis_name, with_aux=with_aux,
                           batch_axes=live)
    out = _shard_map(
        fn, mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=(data_spec, P()) if with_aux else data_spec,
        check_vma=False)(stacked_params, xs)
    if with_aux:
        out, aux = out
        # microbatches average: each tick's aux is a per-microbatch mean
        return out.reshape((b,) + out.shape[2:]), aux / num_microbatches
    return out.reshape((b,) + out.shape[2:])


# ---------------------------------------------------------------------------
# Cross-slice MPMD 1F1B over DCN channels
# ---------------------------------------------------------------------------
class CrossSlicePipeline:
    """Host-driven 1F1B for ONE stage gang of an MPMD pipeline.

    Each stage gang constructs one of these around its OWN (unstacked)
    stage function and its :class:`~tony_tpu.channels.StageLinks`; the
    coordinated effect of every gang running :meth:`value_and_grad` on
    the same microbatch count is exactly the Megatron non-interleaved
    1F1B schedule, spread across slices:

    - stage ``s`` runs ``min(S-1-s, M)`` warmup forwards, then steady
      forward/backward pairs, then drains its remaining backwards;
    - activations flow to ``stage+1`` and cotangents back to ``stage-1``
      over the links' channels. Sends enqueue into the sender's bounded
      window and return — DCN transport of microbatch m±1 overlaps the
      device compute of microbatch m (``sync_transport=True`` defeats
      that on purpose: the serialized baseline the bench contrasts).
    - backward ticks recompute the stage forward from the saved input
      under ``jax.vjp`` (remat-style), the same per-microbatch math as
      the in-slice schedule — loss and gradients are BIT-IDENTICAL to
      :func:`pipeline_value_and_grad` on the same params/microbatches
      (test-pinned), so moving a model across slices never changes what
      it learns.

    The LAST stage owns the loss head: its backward seeds from
    ``loss_head(head_params, stage_fn(params, x), head_mb)`` directly.
    Activation memory is O(in-flight) = O(S - stage) microbatches per
    stage, the 1F1B bound. ``with_aux`` stage functions are not
    supported cross-slice yet (MoE balance losses stay in-slice).

    **Interleaved/looped 1F1B** (``links.interleave`` = v > 1): the gang
    holds v model CHUNKS, chunk j acting as virtual stage ``j*S + s`` of
    a V = S*v deep pipeline (the Megatron looping placement — every
    chunk boundary crosses gangs, over the links' per-chunk ring lanes).
    Each chunk runs its own projection of the V-stage 1F1B schedule in
    its own host thread; a per-gang device lock keeps the compute
    serialization honest, so the win is pure bubble shrink (~1/v) plus
    more DCN transfers in flight per tick. ``value_and_grad`` then takes
    ``params`` as a LIST of v per-chunk pytrees and returns grads the
    same shape; chunk j's math is bit-identical to virtual stage
    ``j*S+s`` of the non-interleaved V-stage schedule (test-pinned).

    Observability: per-call wall and bubble fraction land in the default
    registry (``tony_pipeline_step_seconds``,
    ``tony_pipeline_bubble_fraction{stage=}``), alongside the channels'
    own send/recv walls and queue depths.
    """

    def __init__(self, stage_fn: Callable[[Any, jax.Array], jax.Array],
                 links, *,
                 loss_head: Callable[[Any, jax.Array, Any], jax.Array]
                 | None = None,
                 lookahead: int = 0,
                 sync_transport: bool = False,
                 send_timeout_s: float | None = 120.0,
                 recv_timeout_s: float | None = 120.0,
                 registry=None) -> None:
        from tony_tpu.runtime import metrics as metrics_mod
        from tony_tpu.runtime import tracing
        self._tracing = tracing
        self._tracer = tracing.get_tracer()
        # Deterministic per-step trace ids: every stage gang derives the
        # SAME 128-bit id from the job trace (TONY_TRACE_CTX) + its own
        # call ordinal — one microbatch's journey across gangs
        # reconstructs under one trace id with NO new channel frames
        # (the channel seq tags the hops). The session id + cluster
        # epoch join the seed so a RELAUNCHED trainer (stop-the-world
        # retry resuming from checkpoint, ordinal back at 1) can never
        # re-mint a previous generation's ids — every value here is
        # identical across the stage gangs of one generation.
        import os as _os
        from tony_tpu import constants as _c
        _job_ctx = tracing.parse_env_ctx()
        self._trace_seed = (
            (_job_ctx["tid"] if _job_ctx else "local-pipeline")
            + f":{_os.environ.get(_c.SESSION_ID, '0')}"
            + f":{_os.environ.get(_c.CLUSTER_EPOCH, '0')}")
        self._calls = 0
        self.links = links
        self.stage = links.stage
        self.num_stages = links.num_stages
        #: extra in-flight microbatches beyond the 1F1B minimum: each
        #: stage runs that many more warmup forwards, so activations
        #: already in flight cover the DCN round trip instead of the
        #: backward stalling on it every microbatch (the MPMD-paper
        #: latency-tolerance knob). Costs ``lookahead`` extra saved
        #: microbatch inputs of memory per stage; the accumulation ORDER
        #: of backwards never changes, so results stay bit-identical at
        #: any value.
        self.lookahead = lookahead
        self.sync_transport = sync_transport
        self.send_timeout_s = send_timeout_s
        self.recv_timeout_s = recv_timeout_s
        #: virtual stages per gang (looping placement); 1 = classic
        self.interleave = getattr(links, "interleave", 1) or 1
        self.num_virtual = self.num_stages * self.interleave
        # one device per gang: chunk threads must not interleave their
        # compute dispatches (the lock also keeps busy accounting honest)
        self._device_lock = threading.Lock()
        if links.is_last and loss_head is None:
            raise ValueError("the last stage needs the loss head")
        self._fwd = jax.jit(stage_fn)

        def _bwd(params, saved, cot):
            _, vjp_fn = jax.vjp(lambda p, x: stage_fn(p, x), params, saved)
            return vjp_fn(cot)
        self._bwd = jax.jit(_bwd)

        def _last(params, head_params, saved, head_mb):
            def last_fn(p, hp, x):
                return loss_head(hp, stage_fn(p, x), head_mb)
            lval, vjp_fn = jax.vjp(last_fn, params, head_params, saved)
            dp, dhp, dx = vjp_fn(jnp.ones((), lval.dtype))
            return lval.astype(jnp.float32), dp, dhp, dx
        self._last = jax.jit(_last) if links.is_last else None
        reg = registry if registry is not None \
            else metrics_mod.get_default()
        self._step_hist = reg.histogram(
            "tony_pipeline_step_seconds",
            help="wall seconds per cross-slice 1F1B value_and_grad call",
            stage=str(self.stage))
        self._bubble_gauge = reg.gauge(
            "tony_pipeline_bubble_fraction",
            help="1 - device-busy/wall for the last 1F1B call (this "
                 "stage's pipeline bubble + transport stall share)",
            stage=str(self.stage))
        self._mb_counter = reg.counter(
            "tony_pipeline_microbatches_total",
            help="microbatches processed by this stage",
            stage=str(self.stage))

    # The two compute entry points are methods so instrumentation (and
    # the bench's deterministic compute stand-in) can wrap them.
    def _forward_compute(self, params, x):
        return self._fwd(params, x)

    def _backward_compute(self, params, saved, cot):
        return self._bwd(params, saved, cot)

    def _last_compute(self, params, head_params, saved, head_mb):
        return self._last(params, head_params, saved, head_mb)

    def value_and_grad(self, params, *, num_microbatches: int,
                       microbatches: jax.Array | None = None,
                       head_params: Any = None, head_batches: Any = None):
        """Run one global batch through this stage's share of the 1F1B
        schedule; every stage gang must call this with the same
        ``num_microbatches``.

        - stage 0 supplies ``microbatches`` ([M, mb, ...]; later stages
          receive activations off the wire);
        - the last stage supplies ``head_params`` + ``head_batches``
          (pytree with leading [M, mb, ...] batch dims).

        Returns ``(loss, grads, head_grads, dxs)``: ``loss`` (f32
        scalar) and ``head_grads`` are non-None only on the last stage,
        ``dxs`` ([M, mb, ...] input cotangents) only on stage 0;
        ``grads`` matches ``params`` everywhere.

        With ``interleave`` = v > 1, ``params`` is a LIST of v per-chunk
        pytrees and ``grads`` comes back the same shape; the loss head
        fires on the LAST gang (its last chunk is virtual stage V-1) and
        ``dxs`` on the first (its chunk 0 is virtual stage 0).
        """
        import numpy as np

        if self.interleave > 1:
            return self._value_and_grad_interleaved(
                params, num_microbatches=num_microbatches,
                microbatches=microbatches, head_params=head_params,
                head_batches=head_batches)
        links = self.links
        m = num_microbatches
        if links.is_first:
            if microbatches is None:
                raise ValueError("stage 0 must supply microbatches")
            if microbatches.shape[0] != m:
                raise ValueError(
                    f"microbatches leading dim {microbatches.shape[0]} != "
                    f"num_microbatches {m}")
        if links.is_last and (head_batches is None or head_params is None):
            raise ValueError("the last stage must supply head_params and "
                             "head_batches")
        self._calls += 1
        step_tid = self._tracing.deterministic_trace_id(
            f"{self._trace_seed}:step:{self._calls}")
        root_sid = self._tracing.deterministic_span_id(
            f"{step_tid}:root")
        stage_sid = self._tracing.deterministic_span_id(
            f"{step_tid}:s{self.stage}")
        traced = (self._tracer.enabled
                  and self._tracing.deterministic_sample(
                      step_tid, self._tracer.sample_rate))
        t_start = time.perf_counter()
        busy = 0.0
        saved: dict[int, jax.Array] = {}
        grads = jax.tree.map(jnp.zeros_like, params)
        hgrads = (jax.tree.map(jnp.zeros_like, head_params)
                  if links.is_last else None)
        loss_acc = jnp.zeros((), jnp.float32) if links.is_last else None
        dx_list: list[jax.Array] = []

        def _send(sender, arr):
            return sender.send(np.asarray(arr), sync=self.sync_transport,
                               timeout=self.send_timeout_s)

        def _mb_span(name: str, i: int, t0: float, seq_in: int,
                     seq_out: int) -> None:
            # per-microbatch span under this stage's step span: the hop
            # seq(s) let a cross-gang reader stitch microbatch i's
            # journey (sender and receiver tag the SAME seq)
            attrs = {"stage": self.stage, "mb": i}
            if seq_in >= 0:
                attrs["seq"] = seq_in
            if seq_out >= 0:
                attrs.setdefault("seq", seq_out)
                attrs["seq_out"] = seq_out
            self._tracer.record_span(
                name, time.perf_counter() - t0, trace_id=step_tid,
                parent_id=stage_sid, **attrs)

        def do_forward(i: int) -> None:
            nonlocal busy
            tmb = time.perf_counter()
            seq_in = seq_out = -1
            if links.is_first:
                x = microbatches[i]
            else:
                x = jnp.asarray(links.act_in.recv(self.recv_timeout_s))
                seq_in = links.act_in.last_seq
            saved[i] = x
            if links.is_last:
                if traced:
                    _mb_span("pipeline.forward", i, tmb, seq_in, seq_out)
                return      # the last stage folds its forward into _last
            t0 = time.perf_counter()
            out = self._forward_compute(params, x)
            out_host = np.asarray(out)      # device sync: compute wall ends
            busy += time.perf_counter() - t0
            seq_out = _send(links.act_out, out_host)
            if traced:
                _mb_span("pipeline.forward", i, tmb, seq_in, seq_out)

        def do_backward(i: int) -> None:
            nonlocal busy, grads, hgrads, loss_acc
            tmb = time.perf_counter()
            seq_in = seq_out = -1
            if links.is_last:
                head_mb = jax.tree.map(lambda a: a[i], head_batches)
                t0 = time.perf_counter()
                lval, dp, dhp, dx = self._last_compute(
                    params, head_params, saved.pop(i), head_mb)
                loss_acc = loss_acc + lval
                grads = jax.tree.map(jnp.add, grads, dp)
                hgrads = jax.tree.map(jnp.add, hgrads, dhp)
                dx_host = np.asarray(dx)
                busy += time.perf_counter() - t0
            else:
                cot = jnp.asarray(links.grad_in.recv(self.recv_timeout_s))
                seq_in = links.grad_in.last_seq
                t0 = time.perf_counter()
                dp, dx = self._backward_compute(params, saved.pop(i), cot)
                grads = jax.tree.map(jnp.add, grads, dp)
                dx_host = np.asarray(dx)
                busy += time.perf_counter() - t0
            if links.is_first:
                dx_list.append(jnp.asarray(dx_host))
            else:
                seq_out = _send(links.grad_out, dx_host)
            if traced:
                _mb_span("pipeline.backward", i, tmb, seq_in, seq_out)
            self._mb_counter.inc()

        # the non-interleaved 1F1B schedule in host form: warmup
        # forwards, steady F/B pairs, backward drain
        warmup = min(self.num_stages - 1 - self.stage + self.lookahead, m)
        for i in range(warmup):
            do_forward(i)
        for i in range(m):
            j = i + warmup
            if j < m:
                do_forward(j)
            do_backward(i)

        grads = jax.tree.map(lambda g: g / m, grads)
        loss = None
        if links.is_last:
            loss = loss_acc / m
            hgrads = jax.tree.map(lambda g: g / m, hgrads)
        dxs = jnp.stack(dx_list) / m if links.is_first else None
        wall = time.perf_counter() - t_start
        self._step_hist.observe(wall)
        bubble = max(0.0, 1.0 - busy / wall) if wall > 0 else 0.0
        self._bubble_gauge.set(bubble)
        if traced:
            # this stage's step span (deterministic span id, parented on
            # the shared per-step root so every gang's spans nest under
            # ONE trace); stage 0 also emits the root itself
            self._tracer.record_span(
                "pipeline.stage", wall, trace_id=step_tid,
                span_id=stage_sid, parent_id=root_sid,
                stage=self.stage, microbatches=m,
                bubble=round(bubble, 4))
            if links.is_first:
                self._tracer.record_span(
                    "pipeline.step", wall, trace_id=step_tid,
                    span_id=root_sid, step=self._calls,
                    num_stages=self.num_stages, microbatches=m)
        return loss, grads, hgrads, dxs

    def _value_and_grad_interleaved(self, params_list, *,
                                    num_microbatches: int,
                                    microbatches=None,
                                    head_params=None, head_batches=None):
        """The interleaved schedule: chunk j is virtual stage
        ``g = j*S + s`` of the V-stage pipeline, driven by its own host
        thread running exactly the per-stage projection of the V-stage
        non-interleaved 1F1B schedule (warmup ``min(V-1-g+lookahead, m)``
        forwards, then F/B pairs). Recvs block on the per-chunk lanes, so
        global ordering emerges from dataflow — no cross-gang clock.
        Per-chunk grads accumulate in microbatch order, which is what
        makes chunk j bit-identical to stacked stage g of the in-slice
        V-stage schedule."""
        import numpy as np

        links = self.links
        v, S, V = self.interleave, self.num_stages, self.num_virtual
        m = num_microbatches
        if not isinstance(params_list, (list, tuple)) or \
                len(params_list) != v:
            raise ValueError(
                f"interleave={v}: params must be a list/tuple of {v} "
                f"per-chunk pytrees")
        if links.is_first:
            if microbatches is None:
                raise ValueError("stage 0 must supply microbatches")
            if microbatches.shape[0] != m:
                raise ValueError(
                    f"microbatches leading dim {microbatches.shape[0]} "
                    f"!= num_microbatches {m}")
        if links.is_last and (head_batches is None or head_params is None):
            raise ValueError("the last stage must supply head_params and "
                             "head_batches")
        self._calls += 1
        step_tid = self._tracing.deterministic_trace_id(
            f"{self._trace_seed}:step:{self._calls}")
        root_sid = self._tracing.deterministic_span_id(f"{step_tid}:root")
        stage_sid = self._tracing.deterministic_span_id(
            f"{step_tid}:s{self.stage}")
        traced = (self._tracer.enabled
                  and self._tracing.deterministic_sample(
                      step_tid, self._tracer.sample_rate))
        t_start = time.perf_counter()
        busy = [0.0] * v
        results: list = [None] * v
        failures: list = []

        def _send(sender, arr):
            return sender.send(np.asarray(arr), sync=self.sync_transport,
                               timeout=self.send_timeout_s)

        def run_chunk(j: int) -> None:
            g = j * S + self.stage
            params = params_list[j]
            act_in = links.act_ins[j]
            act_out = links.act_outs[j]
            grad_in = links.grad_ins[j]
            grad_out = links.grad_outs[j]
            saved: dict[int, jax.Array] = {}
            grads = jax.tree.map(jnp.zeros_like, params)
            hgrads = (jax.tree.map(jnp.zeros_like, head_params)
                      if g == V - 1 else None)
            loss_acc = jnp.zeros((), jnp.float32) if g == V - 1 else None
            dx_list: list[jax.Array] = []

            def do_forward(i: int) -> None:
                if g == 0:
                    x = microbatches[i]
                else:
                    x = jnp.asarray(act_in.recv(self.recv_timeout_s))
                saved[i] = x
                if g == V - 1:
                    return      # last virtual stage folds fwd into _last
                with self._device_lock:
                    t0 = time.perf_counter()
                    out = self._forward_compute(params, x)
                    out_host = np.asarray(out)
                    busy[j] += time.perf_counter() - t0
                _send(act_out, out_host)

            def do_backward(i: int) -> None:
                nonlocal grads, hgrads, loss_acc
                if g == V - 1:
                    head_mb = jax.tree.map(lambda a: a[i], head_batches)
                    with self._device_lock:
                        t0 = time.perf_counter()
                        lval, dp, dhp, dx = self._last_compute(
                            params, head_params, saved.pop(i), head_mb)
                        loss_acc = loss_acc + lval
                        grads = jax.tree.map(jnp.add, grads, dp)
                        hgrads = jax.tree.map(jnp.add, hgrads, dhp)
                        dx_host = np.asarray(dx)
                        busy[j] += time.perf_counter() - t0
                else:
                    cot = jnp.asarray(grad_in.recv(self.recv_timeout_s))
                    with self._device_lock:
                        t0 = time.perf_counter()
                        dp, dx = self._backward_compute(
                            params, saved.pop(i), cot)
                        grads = jax.tree.map(jnp.add, grads, dp)
                        dx_host = np.asarray(dx)
                        busy[j] += time.perf_counter() - t0
                if g == 0:
                    dx_list.append(jnp.asarray(dx_host))
                else:
                    _send(grad_out, dx_host)
                self._mb_counter.inc()

            warmup = min(V - 1 - g + self.lookahead, m)
            for i in range(warmup):
                do_forward(i)
            for i in range(m):
                k = i + warmup
                if k < m:
                    do_forward(k)
                do_backward(i)
            results[j] = (grads, loss_acc, hgrads, dx_list)

        def chunk_main(j: int) -> None:
            try:
                run_chunk(j)
            except BaseException as exc:   # propagated after join
                failures.append((j, exc))

        threads = [threading.Thread(target=chunk_main, args=(j,),
                                    name=f"tony-pp-chunk{j}", daemon=True)
                   for j in range(v)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if failures:
            j, exc = failures[0]
            raise RuntimeError(
                f"interleaved chunk {j} (virtual stage "
                f"{j * S + self.stage}) failed") from exc

        grads_out = [jax.tree.map(lambda a: a / m, r[0]) for r in results]
        loss = hgrads = dxs = None
        if links.is_last:
            loss = results[v - 1][1] / m
            hgrads = jax.tree.map(lambda a: a / m, results[v - 1][2])
        if links.is_first:
            dxs = jnp.stack(results[0][3]) / m
        wall = time.perf_counter() - t_start
        self._step_hist.observe(wall)
        bubble = max(0.0, 1.0 - sum(busy) / wall) if wall > 0 else 0.0
        self._bubble_gauge.set(bubble)
        if traced:
            self._tracer.record_span(
                "pipeline.stage", wall, trace_id=step_tid,
                span_id=stage_sid, parent_id=root_sid, stage=self.stage,
                microbatches=m, interleave=v, bubble=round(bubble, 4))
            if links.is_first:
                self._tracer.record_span(
                    "pipeline.step", wall, trace_id=step_tid,
                    span_id=root_sid, step=self._calls,
                    num_stages=self.num_stages, microbatches=m)
        return loss, grads_out, hgrads, dxs
