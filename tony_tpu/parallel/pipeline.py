"""Pipeline parallelism: GPipe-style microbatch pipelining over ``pp``.

Green-field for the TPU build (SURVEY.md §2.3: PP absent from the reference).
Stages live on different devices along the mesh's ``pp`` axis; activations
hop stage→stage with ``lax.ppermute`` (point-to-point, so pp tolerates DCN);
microbatches fill the pipeline GPipe-fashion: with S stages and M
microbatches the steady loop runs M+S-1 ticks and bubble overhead is
(S-1)/(M+S-1). Differentiable end-to-end: AD through scan+ppermute yields
the reverse pipeline schedule automatically.

Constraint: the stage function must map activations to activations of the
same shape/dtype (natural for transformer blocks). Per-stage params are
stacked on a leading [S, ...] axis, sharded P("pp") — each device reads only
its own stage's slice.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(stage_params: Any, microbatches: jax.Array, *,
                    stage_fn: Callable[[Any, jax.Array], Any],
                    axis_name: str, with_aux: bool,
                    batch_axes: tuple[str, ...]) -> Any:
    """Per-device pipeline body (inside shard_map over ``axis_name``).

    stage_params: this stage's params (leading [1, ...] shard dim squeezed).
    microbatches: [M, mb, ...] — replicated input; stage 0 consumes it.
    Returns [M, mb, ...] final-stage outputs, replicated via psum; with
    ``with_aux`` the stage_fn returns (out, scalar) and the scalar is
    accumulated over VALID ticks only (warmup/drain ticks run the stage on
    garbage state whose aux must not count), summed over stages, and
    averaged over the batch axes.
    """
    s = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda x: x[0], stage_params)
    m = microbatches.shape[0]
    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    aux0 = jnp.zeros((), jnp.float32)
    shift = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        # stage 0 ingests microbatch t while t < M; later stages use the
        # activation that arrived from the previous stage last tick
        inp = jnp.where(stage == 0, microbatches[jnp.minimum(t, m - 1)], state)
        res = stage_fn(params, inp)
        out, aux = res if with_aux else (res, aux0)
        # stage s processes microbatch t-s at tick t; anything else is
        # pipeline bubble running on zeros/garbage
        valid = jnp.logical_and(t - stage >= 0, t - stage < m)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        # the final stage finishes microbatch t-(S-1) at tick t
        widx = t - (s - 1)
        take = jnp.logical_and(stage == s - 1, widx >= 0)
        slot = jnp.clip(widx, 0, m - 1)
        outputs = outputs.at[slot].set(
            jnp.where(take, out, outputs[slot]))
        state = lax.ppermute(out, axis_name, shift)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = lax.scan(
        tick, (state, outputs, aux0), jnp.arange(m + s - 1, dtype=jnp.int32))
    # only the last stage holds real outputs; broadcast around the ring so
    # the result is replicated over pp (out_spec P() below)
    mask = (stage == s - 1).astype(outputs.dtype)
    outputs = lax.psum(outputs * mask, axis_name)
    if not with_aux:
        return outputs
    # stages sum (each holds different layers), microbatches average (the
    # /m outside), batch shards average — replicated on every device
    aux_acc = lax.psum(aux_acc, axis_name)
    for a in batch_axes:
        aux_acc = lax.pmean(aux_acc, a)
    return outputs, aux_acc


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], Any],
                   stacked_params: Any, x: jax.Array, mesh: Mesh, *,
                   num_microbatches: int, axis_name: str = "pp",
                   batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                   with_aux: bool = False,
                   param_specs: Any = None):
    """Run x through S pipeline stages of ``stage_fn``.

    stacked_params: pytree whose leaves lead with the stage axis [S, ...];
    S must equal the ``pp`` mesh axis size (one stage per pp rank).
    x: [B, ...] global batch; must divide into ``num_microbatches``; the
    microbatch dim stays sharded over the live batch axes (dp/fsdp).
    Returns [B, ...] outputs (replicated over pp).

    ``with_aux``: stage_fn returns (out, scalar); the scalars from valid
    (non-bubble) ticks sum over stages and average over microbatches and
    batch shards — the MoE load-balance loss channel; returns (out, aux).
    ``param_specs``: override the default P(pp) per-leaf placement — how
    MoE expert weights additionally shard over ``ep`` inside the stage
    (leaves then arrive in the body already sliced to the rank's experts).
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible into "
                         f"{num_microbatches} microbatches")
    num_stages = jax.tree.leaves(stacked_params)[0].shape[0]

    if axis_name not in mesh.shape or mesh.shape[axis_name] == 1:
        # degenerate: no pp axis — run stages sequentially via scan
        if with_aux:
            def body_aux(carry, p):
                h, acc = carry
                h, aux = stage_fn(p, h)
                return (h, acc + aux), None
            (out, aux), _ = lax.scan(
                body_aux, (x, jnp.zeros((), jnp.float32)), stacked_params)
            return out, aux

        def body(h, p):
            return stage_fn(p, h), None
        out, _ = lax.scan(body, x, stacked_params)
        return out

    pp = mesh.shape[axis_name]
    if num_stages != pp:
        raise ValueError(f"{num_stages} stacked stages but pp axis has "
                         f"{pp} ranks — need exactly one stage per rank")
    mb = b // num_microbatches
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])

    live = tuple(a for a in batch_axes
                 if a in mesh.shape and mesh.shape[a] > 1)
    data_spec = P(None, live if len(live) > 1 else (live[0] if live else None))
    if param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = functools.partial(_pipeline_local, stage_fn=stage_fn,
                           axis_name=axis_name, with_aux=with_aux,
                           batch_axes=live)
    out = jax.shard_map(
        fn, mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=(data_spec, P()) if with_aux else data_spec,
        check_vma=False)(stacked_params, xs)
    if with_aux:
        out, aux = out
        # microbatches average: each tick's aux is a per-microbatch mean
        return out.reshape((b,) + out.shape[2:]), aux / num_microbatches
    return out.reshape((b,) + out.shape[2:])
