"""Ring attention: context parallelism over the mesh's ``cp`` axis.

Green-field for the TPU build (SURVEY.md §2.3 / §5: the reference scales
nodes, not sequence length). Sequence is sharded over ``cp``; each device
holds a Q/K/V chunk, computes blockwise attention against the K/V chunk it
currently holds, then rotates K/V one hop around the ring with
``lax.ppermute`` (ICI neighbor exchange) while accumulating an online
softmax — so peak memory is O(seq/cp) and the full sequence is never
materialized on one chip. Differentiable as-is: the backward pass is the
transposed ring (ppermute has a transpose rule), driven by JAX AD through
the scan.

Numerics follow flash attention: f32 running max ``m``, normalizer ``l`` and
unnormalized output ``o``; fully-masked blocks (causal, future chunks) are
handled with a -1e30 additive mask so ``m`` never becomes -inf.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1.0e30


def _block_attn(q, k, v, m, l, o, scale, q_off, kv_off, causal):
    """One blockwise-attention accumulation step (online softmax).

    q: [B, Sq, H, D]; k/v: [B, Sk, H_kv, D] (H_kv | H — GQA chunks stay
    unexpanded on the ring so the ppermute payload is H/H_kv× smaller;
    grouped einsums read the shared head directly, no materialized
    expansion); m/l: [B, H, Sq]; o: [B, Sq, H, D]. q_off/kv_off are the
    global sequence offsets of the chunks (for causal masking across
    ring hops).
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    if hk != h:
        # blocked grouping (query head j ↔ kv head j // rep), same layout
        # as the flash kernels; [b, hk, rep, q, k] reshapes to the
        # contiguous [b, h, q, k]
        rep = h // hk
        qg = q.reshape(b, sq, hk, rep, d)
        s = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k,
                       preferred_element_type=jnp.float32)
        s = s.reshape(b, h, sq, k.shape[1]) * scale
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        kv_pos = kv_off + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp of masked lanes underflows to 0; correction stays finite because
    # m is floored at _NEG_INF rather than -inf.
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    if hk != h:
        pg = p.reshape(b, hk, h // hk, sq, k.shape[1])
        pv = jnp.einsum("bhrqk,bkhd->bqhrd", pg.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
        pv = pv.reshape(b, sq, h, d)
    else:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                        preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


# Per-chunk attention engine: None = auto (flash kernels on TPU, dense
# online-softmax elsewhere); tests force True to run the flash arm in
# interpret mode.
_USE_FLASH_CHUNKS: bool | None = None


def _flash_chunks() -> bool:
    if _USE_FLASH_CHUNKS is not None:
        return _USE_FLASH_CHUNKS
    import jax
    return jax.default_backend() == "tpu"


def ring_attention_local(q, k, v, *, axis_name: str = "cp",
                         causal: bool = True, scale: float | None = None):
    """Per-shard ring attention body — call inside ``shard_map`` (or any
    SPMD context where ``axis_name`` is bound and the sequence dim is the
    shard axis).

    q, k, v: [B, S_local, H, D] local chunks. Returns [B, S_local, H, D].
    On TPU each hop's chunk runs the flash kernels
    (:func:`tony_tpu.ops.attention.flash_attention_with_lse`) and hops are
    merged by logsumexp — O(S_local) memory per chunk instead of the dense
    [B, H, S_local, S_local] score tensor.
    """
    b, s_loc, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    cp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    q_off = idx * s_loc

    if _flash_chunks() and _flash_block(s_loc) is not None:
        return _ring_flash(q, k, v, axis_name=axis_name, causal=causal,
                           scale=scale, cp=cp, q_off=q_off)

    q32 = q.astype(jnp.float32) if q.dtype == jnp.float64 else q
    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)

    if cp == 1:
        m, l, o = _block_attn(q32, k, v, m0, l0, o0, scale, q_off, q_off,
                              causal)
        return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, hop):
        k_cur, v_cur, m, l, o = carry
        # chunk held at this hop originated on device (idx - hop) mod cp
        kv_off = ((idx - hop) % cp) * s_loc
        m, l, o = _block_attn(q32, k_cur, v_cur, m, l, o, scale, q_off,
                              kv_off, causal)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (_, _, m, l, o), _ = lax.scan(step, (k, v, m0, l0, o0),
                                  jnp.arange(cp, dtype=jnp.int32))
    # causal + f32: every query attends at least to itself, so l > 0
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def _flash_block(s_loc: int) -> int | None:
    """Largest flash block size tiling the local chunk, or None when no
    usable block exists. On REAL TPU the floor is 128: below the
    128-lane tile the Mosaic lowering of the 2-D lse layout is untested,
    and per-grid-step overhead makes flash slower than the dense arm
    there anyway. Interpret mode (the CPU test path) keeps the small
    blocks so the flash-chunk arm stays bit-testable at tiny shapes."""
    import jax
    blocks = ((512, 256, 128) if jax.default_backend() == "tpu"
              else (512, 256, 128, 64, 32, 16, 8))
    for b in blocks:
        if s_loc % b == 0:
            return b
    return None


def _ring_flash(q, k, v, *, axis_name, causal, scale, cp, q_off):
    """Ring body with flash-kernel chunks merged by logsumexp.

    Each hop's chunk falls into one of three causal cases, selected at
    runtime (the kv offset rotates with the hop): entirely in the past
    (full attention, no mask), the diagonal chunk (causal flash), or
    entirely in the future (skipped — contributes o = 0, lse = -1e30,
    which the finite-arithmetic logaddexp merge weights to exactly zero).
    The lse outputs are DIFFERENTIATED (flash_attention_with_lse), so
    JAX AD through the merge + scan yields the transposed ring backward
    with flash backward kernels per chunk."""
    from tony_tpu.ops.attention import flash_attention_with_lse

    out_dtype = q.dtype
    if q.dtype == jnp.float64:      # pallas kernels have no f64 path
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    b, s_loc, h, d = q.shape
    blk = _flash_block(s_loc)
    # narrow-q × wide-kv is the kernels' measured sweet spot (see
    # ops/attention.py); fall back to the square tiling block for chunk
    # lengths the preferred shapes don't divide
    bq = min(256, blk) if s_loc % min(256, blk) == 0 else blk
    bk = next((w for w in (1024, 512, 256) if w >= blk and s_loc % w == 0),
              blk)

    def full_chunk(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=False, scale=scale,
                                          block_q=bq, block_k=bk)
        return o.astype(jnp.float32), lse

    def diag_chunk(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=True, scale=scale,
                                          block_q=bq, block_k=bk)
        return o.astype(jnp.float32), lse

    def future_chunk(q, k, v):
        # output/lse are q-shaped: unaffected by GQA K/V widths
        return (jnp.zeros((b, s_loc, h, d), jnp.float32),
                jnp.full((b, h, s_loc), _NEG_INF, jnp.float32))

    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)

    if cp == 1:
        o, lse = (diag_chunk if causal else full_chunk)(q, k, v)
        return o.astype(out_dtype)

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    idx = lax.axis_index(axis_name)

    def step(carry, hop):
        k_cur, v_cur, o_acc, lse_acc = carry
        kv_off = ((idx - hop) % cp) * s_loc
        if causal:
            case = jnp.where(kv_off > q_off, 2,
                             jnp.where(kv_off == q_off, 1, 0))
            o_c, lse_c = lax.switch(
                case, (full_chunk, diag_chunk, future_chunk), q, k_cur, v_cur)
        else:
            # every hop is a full chunk — no switch, no dead branches
            o_c, lse_c = full_chunk(q, k_cur, v_cur)
        lse_new = jnp.logaddexp(lse_acc, lse_c)         # [B, H, S]
        w_acc = jnp.exp(lse_acc - lse_new)
        w_c = jnp.exp(lse_c - lse_new)
        to_bshd = lambda w: w.transpose(0, 2, 1)[..., None]
        o_acc = o_acc * to_bshd(w_acc) + o_c * to_bshd(w_c)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o_acc, lse_new), None

    (_, _, o, _), _ = lax.scan(step, (k, v, o0, lse0),
                               jnp.arange(cp, dtype=jnp.int32))
    return o.astype(out_dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                   scale: float | None = None,
                   batch_axes: Sequence[str] = ("dp", "fsdp"),
                   seq_axis: str = "cp", head_axis: str = "tp"):
    """Context-parallel attention over global [B, S, H, D] arrays.

    A ``shard_map`` island intended for use inside a jitted model: batch over
    dp/fsdp, sequence over cp, heads over tp. Axes missing from ``mesh`` (or
    of size 1) are dropped from the specs automatically.

    GQA K/V (fewer heads than Q) ride the ring UNEXPANDED — the ring's
    inter-chip traffic IS the K/V rotation, so grouped heads cut the
    ppermute payload by H/H_kv. Head-sharding discipline: the local
    arms pair local query head j with local kv head j // rep, which is
    only the GLOBAL pairing when K/V heads shard over the SAME axis as
    Q's (or none is live). So kv heads shard over ``head_axis`` when
    they divide it; otherwise (H_kv < tp) K/V expand to full width
    first — correctness over the payload saving.
    """
    from tony_tpu.parallel.sharding import attention_spec
    spec, s_spec = attention_spec(mesh, batch_axes, seq_axis, head_axis)
    h, hk = q.shape[2], k.shape[2]
    if hk != h and (hk <= 0 or h % hk):
        raise ValueError(f"kv heads ({hk}) must divide heads ({h})")
    if hk != h:
        tp = mesh.shape.get(head_axis, 1) if head_axis else 1
        if hk % max(tp, 1):
            rep = h // hk
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

    if s_spec is None:
        # no cp axis: plain (still blockwise/online-softmax) local attention
        fn = functools.partial(_single_chunk, causal=causal, scale=scale)
    else:
        fn = functools.partial(ring_attention_local, axis_name=seq_axis,
                               causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _single_chunk(q, k, v, *, causal, scale):
    b, s, h, d = q.shape
    scale = (d ** -0.5) if scale is None else scale
    blk = _flash_block(s)
    if _flash_chunks() and blk is not None:
        # same engine selection (and f64→f32 cast) as the ring hops
        from tony_tpu.ops.attention import flash_attention
        out_dtype = q.dtype
        if q.dtype == jnp.float64:
            q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=blk, block_k=blk).astype(out_dtype)
    m = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s), jnp.float32)
    o = jnp.zeros((b, s, h, d), jnp.float32)
    m, l, o = _block_attn(q, k, v, m, l, o, scale, 0, 0, causal)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
