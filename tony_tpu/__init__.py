"""tony_tpu — a TPU-native distributed-training orchestration framework.

Brand-new rebuild of the capability set of LinkedIn TonY (reference mounted at
/root/reference) for Cloud TPU pod slices and the JAX/XLA runtime. See
SURVEY.md for the blueprint and docs/ for user documentation.

Common entry points (lazily imported so ``import tony_tpu`` stays cheap and
jax-free for pure-orchestration uses)::

    tony_tpu.runtime              # task-side bootstrap: initialize(), mesh()
    tony_tpu.TonyClient           # programmatic job submission
    tony_tpu.TonyConfig           # the tony.* config system
    tony_tpu.CheckpointManager    # orbax checkpoint/resume helper
    tony_tpu.FileSplitReader      # sharded data feed (TONY1 / lines / fixed)
"""

__version__ = "0.1.0"

_LAZY = {
    "TonyClient": ("tony_tpu.client.client", "TonyClient"),
    "TonyConfig": ("tony_tpu.conf.config", "TonyConfig"),
    "CheckpointManager": ("tony_tpu.models.checkpoint", "CheckpointManager"),
    "FileSplitReader": ("tony_tpu.io.reader", "FileSplitReader"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'tony_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
