"""tony_tpu — a TPU-native distributed-training orchestration framework.

Brand-new rebuild of the capability set of LinkedIn TonY (reference mounted at
/root/reference) for Cloud TPU pod slices and the JAX/XLA runtime. See
SURVEY.md for the blueprint.
"""

__version__ = "0.1.0"
