from tony_tpu.workflow.jobtype import TonyJob

__all__ = ["TonyJob"]
