"""Workflow-scheduler jobtype: flat job properties → a tony submission.

Analog of the reference's tony-azkaban module (reference: tony-azkaban/src/
main/java/com/linkedin/tony/azkaban/TensorFlowJob.java:24-141 and
TensorFlowJobArg.java): a workflow scheduler (Azkaban, Airflow, Oozie, cron)
describes a training job as a flat key=value property map —

    executes            = python train.py
    src_dir             = src
    python_venv         = venv.zip
    python_binary_path  = python3.11
    task_params         = --epochs 3
    worker_env.FOO      = bar          # forwarded into every task's env
    tony.worker.instances = 4          # any tony.* key → generated tony.xml

— and this jobtype translates it into (a) a generated ``tony.xml`` holding
every ``tony.*`` property (the reference writes _tony-conf-<jobid>/tony.xml,
:129-137) and (b) the main-args list for the submission CLI (:88-126). The
scheduler then either calls :meth:`TonyJob.run` in-process or executes the
printed command line.

Scheduler integration is one property file plus::

    python -m tony_tpu.workflow.jobtype --props job.properties

(Airflow: ``PythonOperator(python_callable=TonyJob(props).run)``.)
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from tony_tpu.conf.config import TonyConfig

log = logging.getLogger(__name__)

WORKER_ENV_PREFIX = "worker_env."
TONY_CONF_PREFIX = "tony."

#: flat-prop name → CLI flag, in emission order (reference:
#: TensorFlowJobArg.java — hdfs_classpath is YARN-specific and dropped).
_SIMPLE_ARGS = ("src_dir", "task_params", "python_binary_path",
                "python_venv", "executes")


def parse_properties(path: str) -> dict[str, str]:
    """Read a java-style .properties file (k=v, # comments)."""
    props: dict[str, str] = {}
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith(("#", "!")):
                continue
            k, sep, v = line.partition("=")
            if not sep:
                continue
            props[k.strip()] = v.strip()
    return props


class TonyJob:
    """Translate a flat property map into a tony CLI invocation."""

    def __init__(self, props: dict[str, str], job_id: str = "job",
                 working_dir: str | None = None) -> None:
        self.props = dict(props)
        self.job_id = job_id
        self.working_dir = working_dir or os.getcwd()
        # Generated conf lives in its own subdir like the reference's
        # _tony-conf-<jobid>/tony.xml (TensorFlowJob.java:34-36).
        self.conf_dir = os.path.join(self.working_dir,
                                     f"_tony-conf-{self.job_id}")
        self.conf_file = os.path.join(self.conf_dir, "tony.xml")

    # ------------------------------------------------------------------
    def write_conf(self) -> str:
        """Write every tony.* property into the generated tony.xml
        (reference: TensorFlowJob.getMainArguments:126-137)."""
        confs = {k: v for k, v in self.props.items()
                 if k.startswith(TONY_CONF_PREFIX)}
        os.makedirs(self.conf_dir, exist_ok=True)
        TonyConfig(confs, load_defaults=False).write_xml(self.conf_file)
        return self.conf_file

    def main_args(self) -> list[str]:
        """The submission-CLI argument list (reference: getMainArguments:88).
        ``executes`` is required — a workflow job with nothing to execute is
        a misconfiguration worth failing loudly on."""
        if "executes" not in self.props:
            raise ValueError("workflow job needs an 'executes' property")
        args = ["submit", "--conf_file", self.write_conf()]
        for name in _SIMPLE_ARGS:
            if name in self.props:
                # --flag=value single-token form: a value starting with a
                # dash (task_params = --verbose) would otherwise be eaten
                # by argparse as an option.
                args.append(f"--{name}={self.props[name]}")
        for key, value in sorted(self.props.items()):
            if key.startswith(WORKER_ENV_PREFIX):
                env_name = key[len(WORKER_ENV_PREFIX):]
                args.append(f"--shell_env={env_name}={value}")
        return args

    def command_line(self) -> list[str]:
        """Full argv a scheduler can exec directly."""
        return [sys.executable, "-m", "tony_tpu.client.cli"] + self.main_args()

    def run(self) -> int:
        """Submit in-process and return the job's exit code."""
        from tony_tpu.client import cli
        args = self.main_args()
        log.info("workflow jobtype submitting: %s", " ".join(args))
        return cli.main(args)


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(prog="tony-workflow-job")
    parser.add_argument("--props", required=True,
                        help="path to the job .properties file")
    parser.add_argument("--job_id", default="job")
    args = parser.parse_args(argv)
    job = TonyJob(parse_properties(args.props), job_id=args.job_id)
    return job.run()


if __name__ == "__main__":
    raise SystemExit(main())
