"""tonylint: AST-based invariant checker for the tony_tpu tree.

The orchestrator's whole value is that it babysits everything and never
dies with the job — and the repo's reliability bugs keep being instances
of the same few static patterns: a blocking call made while holding a
lock (the channel-plane hangs), a leaked fd (the launch leak now watched
at runtime by ``tony_task_open_fds``), a proto wire change that was not
strictly additive, a bare ``except`` that eats the evidence in a server
hot loop. This module encodes those hard-won disciplines as ~8 checkers
so every future PR inherits them for free instead of re-learning them in
review::

    python -m tony_tpu.devtools.lint [paths...]          # exit 1 on findings
    python -m tony_tpu.devtools.lint --update-wire-manifest

Checkers (table with rationale in ``docs/static-analysis.md``):

========  ==============================================================
TL001     blocking-while-locked: socket send/recv/accept/connect,
          ``time.sleep``, ``subprocess.*``, thread ``.join()``, channel
          ``send``/``send_bytes``/``recv_bytes``, frame I/O, and
          foreign ``.wait()`` lexically inside a ``with <lock>`` block.
TL002     lock-discipline: attributes a class declares guarded via a
          ``# guarded-by: _lock`` comment accessed outside a ``with``
          on that lock.
TL003     thread-hygiene: every ``threading.Thread`` gets a ``tony-``-
          prefixed ``name`` and is either ``daemon=True`` or provably
          joined in the same module.
TL004     fd-hygiene: ``socket.socket()`` / ``open()`` results bound to
          locals must be closed (``with``, ``try/finally``, a
          same-function ``.close()``) or escape ownership.
TL005     broad-except: bare ``except:`` / ``except Exception`` that
          neither re-raises, logs, nor flight-records.
TL006     proto-additivity: ``tony.proto`` diffed against the committed
          ``wire_manifest.json`` — renumbering or reusing a released
          field number is an error; adding is fine and
          ``--update-wire-manifest`` records it.
TL007     frame-exhaustiveness: every frame/op constant in
          ``serving/protocol.py`` and ``channels/channel.py`` has a
          dispatch arm somewhere under ``tony_tpu/``.
TL008     unobserved-series: every ``tony_*`` metric series, jhist
          event type, and ``tony.*`` config key appears in its docs
          table, and vice versa (the one implementation behind the
          bijection tests in ``tests/test_tracing.py`` /
          ``tests/test_config.py``).
========  ==============================================================

Suppression is a checked-in **baseline** (``devtools/lint_baseline.json``)
keyed per ``(checker, path, symbol)`` — never per line number — so the
gate is ratcheting: pre-existing findings stay suppressed, new code
cannot add any, and shrinking the baseline is always legal.

Dependency-free on purpose (stdlib ``ast`` + ``json`` + ``re`` only): it
must run on any machine that can run the tests, including inside the
tier-1 self-check (``tests/test_lint.py``) and the bench's ``_lint_arm``.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys

#: repo root (the directory holding tony_tpu/, docs/, tests/).
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join("tony_tpu", "devtools",
                                "lint_baseline.json")
WIRE_MANIFEST = os.path.join("tony_tpu", "rpc", "proto",
                             "wire_manifest.json")
PROTO_FILE = os.path.join("tony_tpu", "rpc", "proto", "tony.proto")

CHECKERS = ("TL001", "TL002", "TL003", "TL004",
            "TL005", "TL006", "TL007", "TL008")


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    path: str        # repo-relative, posix separators
    line: int
    symbol: str      # stable suppression key: qualname / constant / series
    message: str
    hint: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.checker, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.checker} "
                f"[{self.symbol}] {self.message}  (fix: {self.hint})")


@dataclasses.dataclass
class Module:
    path: str        # repo-relative posix path (or absolute if outside)
    abspath: str
    source: str
    lines: list[str]
    tree: ast.AST


# ---------------------------------------------------------------------------
# shared AST plumbing
# ---------------------------------------------------------------------------
def _relpath(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        return os.path.relpath(ap, REPO_ROOT).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def load_module(path: str) -> Module | None:
    """Parse one file; unparseable files are their own loud failure at
    import/test time, not a lint concern — skipped with a stderr note."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        print(f"tonylint: skipping unparseable {path}: {e}",
              file=sys.stderr)
        return None
    return Module(path=_relpath(path), abspath=os.path.abspath(path),
                  source=source, lines=source.splitlines(), tree=tree)


def scan_paths(paths: list[str]) -> list[Module]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    mods = []
    for f in files:
        m = load_module(f)
        if m is not None:
            mods.append(m)
    return mods


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _qualnames(tree: ast.AST) -> dict[ast.AST, str]:
    """Map every node to its enclosing scope's qualified name — the
    stable symbol a baseline entry suppresses by."""
    out: dict[ast.AST, str] = {}

    def walk(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = (f"{scope}.{child.name}" if scope
                               else child.name)
            out[child] = child_scope or "<module>"
            walk(child, child_scope)

    out[tree] = "<module>"
    walk(tree, "")
    return out


def _body_nodes(node: ast.AST):
    """Every node lexically under ``node`` EXCLUDING nested function /
    lambda bodies: code inside a closure is not executed where it is
    written, so lock-scope checkers must not attribute it to the
    enclosing block."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# TL001: blocking call while holding a lock
# ---------------------------------------------------------------------------
#: a ``with`` context expression whose last segment matches this is a
#: lock (Lock, RLock, Condition — the repo's naming convention).
_LOCKISH = re.compile(r"(^|_)(lock|cv|mutex|cond|condition)$")

#: attribute calls that block on the network / another thread / a child
#: process. ``.wait()`` is special-cased (fine on the held condition,
#: a deadlock invitation on anything else) and ``.join()`` is
#: heuristically filtered from string joins below.
_BLOCKING_ATTRS = {
    "sleep", "sendall", "send", "recv", "recv_into", "accept",
    "connect", "connect_ex", "sendto", "recvfrom", "makefile",
    "getaddrinfo", "create_connection", "send_bytes", "recv_bytes",
    "drain",
}
_BLOCKING_NAMES = {"sleep", "recv_frame", "send_frame", "recv_exact",
                   "create_connection"}


def _is_string_join(call: ast.Call) -> bool:
    """``sep.join(parts)`` vs ``thread.join(timeout)``: a thread join
    takes no args or a numeric/keyword timeout; a string join takes an
    iterable. ``os.path.join`` is excluded by its receiver chain."""
    recv = call.func.value if isinstance(call.func, ast.Attribute) else None
    if isinstance(recv, ast.Constant):
        return True                      # "".join / b"".join
    if _last_segment(recv) in ("path", "os", "posixpath", "ntpath"):
        return True
    if len(call.args) > 1:
        return True
    if call.args:
        a = call.args[0]
        if not (isinstance(a, ast.Constant)
                and isinstance(a.value, (int, float))):
            return True                  # join(parts): an iterable arg
    return False


def _blocking_call_reason(call: ast.Call,
                          held_locks: list[str]) -> str | None:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in _BLOCKING_NAMES:
            return func.id
        return None
    if not isinstance(func, ast.Attribute):
        return None
    dotted = _dotted(func) or func.attr
    root = dotted.split(".", 1)[0]
    if root == "subprocess":
        return dotted
    if func.attr == "join":
        if _is_string_join(call):
            return None
        return dotted + "()"
    if func.attr == "wait":
        # waiting on the condition you hold RELEASES it (fine); waiting
        # on anything else while holding a lock is the deadlock shape.
        recv = _dotted(func.value)
        if recv is not None and recv in held_locks:
            return None
        return dotted + "()"
    if func.attr in _BLOCKING_ATTRS:
        return dotted + "()"
    return None


def check_blocking_under_lock(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    quals = _qualnames(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        locks = []
        for item in node.items:
            seg = _last_segment(item.context_expr)
            if seg and _LOCKISH.search(seg):
                locks.append(_dotted(item.context_expr) or seg)
        if not locks:
            continue
        for inner in _body_nodes(node):
            if not isinstance(inner, ast.Call):
                continue
            reason = _blocking_call_reason(inner, locks)
            if reason is None:
                continue
            findings.append(Finding(
                "TL001", mod.path, inner.lineno,
                quals.get(inner, "<module>"),
                f"blocking call {reason} while holding "
                f"{' + '.join(locks)}",
                "move the blocking call outside the with-block, or "
                "snapshot state under the lock and act on it after "
                "release"))
    return findings


# ---------------------------------------------------------------------------
# TL002: guarded-by lock discipline
# ---------------------------------------------------------------------------
_GUARDED_BY = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


def _guarded_decls(cls: ast.ClassDef,
                   lines: list[str]) -> dict[str, tuple[str, int]]:
    """``self.X = ...  # guarded-by: _lock`` declarations anywhere in the
    class body -> {attr: (lock_attr, decl_line)}."""
    decls: dict[str, tuple[str, int]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                m = _GUARDED_BY.search(lines[node.lineno - 1]) \
                    if node.lineno - 1 < len(lines) else None
                if m:
                    decls[t.attr] = (m.group(1), node.lineno)
    return decls


def check_lock_discipline(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        decls = _guarded_decls(cls, mod.lines)
        if not decls:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue        # construction precedes sharing
            findings.extend(_scan_guarded_fn(mod, cls, fn, decls))
    return findings


def _scan_guarded_fn(mod: Module, cls: ast.ClassDef, fn: ast.AST,
                     decls: dict[str, tuple[str, int]]) -> list[Finding]:
    findings = []
    guarded_here: list[tuple[ast.AST, set[str]]] = []

    def locks_held_at(target: ast.AST) -> set[str]:
        held: set[str] = set()
        for scope, locks in guarded_here:
            if target in scope_members[id(scope)]:
                held |= locks
        return held

    # precompute with-block membership (lexical, excluding nested defs)
    scope_members: dict[int, set[ast.AST]] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locks = set()
            for item in node.items:
                seg = _last_segment(item.context_expr)
                if seg:
                    locks.add(seg)
            if locks:
                guarded_here.append((node, locks))
                scope_members[id(node)] = set(_body_nodes(node))
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in decls):
            continue
        lock, _decl_line = decls[node.attr]
        if lock in locks_held_at(node):
            continue
        findings.append(Finding(
            "TL002", mod.path, node.lineno,
            f"{cls.name}.{node.attr}",
            f"self.{node.attr} is declared guarded-by {lock} but "
            f"accessed outside `with self.{lock}`",
            f"wrap the access in `with self.{lock}:` (or snapshot the "
            f"value under the lock)"))
    return findings


# ---------------------------------------------------------------------------
# TL003: thread hygiene
# ---------------------------------------------------------------------------
def _thread_name_ok(call: ast.Call) -> tuple[bool, str]:
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value.startswith("tony-"), repr(v.value)
        if isinstance(v, ast.JoinedStr) and v.values:
            first = v.values[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                return first.value.startswith("tony-"), \
                    f"f{first.value!r}..."
        return False, "<dynamic>"
    return False, "<unnamed>"


def _module_join_receivers(tree: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not _is_string_join(node)):
            seg = _last_segment(node.func.value)
            if seg:
                out.add(seg)
    return out


def _loop_vars_over(tree: ast.AST, container: str) -> set[str]:
    """names bound by ``for v in <container>`` loops anywhere."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.For) \
                and _last_segment(node.iter) == container \
                and isinstance(node.target, ast.Name):
            out.add(node.target.id)
    return out


def check_thread_hygiene(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    quals = _qualnames(mod.tree)
    joins = _module_join_receivers(mod.tree)
    # map Thread-call -> the name it (or its containing listcomp) binds
    bound: dict[ast.Call, str] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target_seg = _last_segment(node.targets[0])
        if not target_seg:
            continue
        value = node.value
        calls = [value] if isinstance(value, ast.Call) else \
            [value.elt] if isinstance(value, ast.ListComp) \
            and isinstance(value.elt, ast.Call) else []
        for c in calls:
            bound[c] = target_seg
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and _last_segment(node.func) == "Thread"):
            continue
        sym = quals.get(node, "<module>")
        ok, shown = _thread_name_ok(node)
        if not ok:
            findings.append(Finding(
                "TL003", mod.path, node.lineno, sym,
                f"thread name {shown} is not 'tony-'-prefixed",
                "pass name='tony-<role>' so stacks, `py-spy` and "
                "flight dumps attribute the thread"))
        daemon = any(kw.arg == "daemon"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True
                     for kw in node.keywords)
        if daemon:
            continue
        target = bound.get(node)
        joined = target is not None and (
            target in joins
            or bool(_loop_vars_over(mod.tree, target) & joins))
        if not joined:
            findings.append(Finding(
                "TL003", mod.path, node.lineno, sym,
                "thread is neither daemon=True nor provably joined in "
                "this module",
                "pass daemon=True, or bind the thread and .join() it "
                "on every exit path"))
    return findings


# ---------------------------------------------------------------------------
# TL004: fd hygiene
# ---------------------------------------------------------------------------
_FD_FACTORIES = {"open", "socket", "create_connection", "socketpair"}


def _is_fd_factory(call: ast.Call) -> bool:
    seg = _last_segment(call.func)
    if seg not in _FD_FACTORIES:
        return False
    if seg == "socket":
        # socket.socket(...) / socket(...) — not e.g. x.socket attribute
        root = _dotted(call.func)
        return root in ("socket", "socket.socket")
    return True


def check_fd_hygiene(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        findings.extend(_scan_fd_fn(mod, fn))
    return findings


def _scan_fd_fn(mod: Module, fn: ast.AST) -> list[Finding]:
    quals_prefix = fn.name
    opened: dict[str, int] = {}             # var -> lineno
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_fd_factory(node.value)):
            opened[node.targets[0].id] = node.lineno
    if not opened:
        return []
    closed: set[str] = set()
    escaped: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("close", "detach", "shutdown") \
                    and isinstance(node.func.value, ast.Name):
                closed.add(node.func.value.id)
            # ownership transfer: the fd passed to another call
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in opened:
                        escaped.add(sub.id)
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in opened:
                    escaped.add(sub.id)
        elif isinstance(node, ast.Assign):
            # stored on self / a container: lifetime managed elsewhere
            if isinstance(node.value, (ast.Name, ast.Tuple, ast.List,
                                       ast.Dict)):
                names = {s.id for s in ast.walk(node.value)
                         if isinstance(s, ast.Name)}
                if names & set(opened):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            escaped |= names & set(opened)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Name) and sub.id in opened:
                        closed.add(sub.id)      # contextlib.closing etc.
    out = []
    for var, line in sorted(opened.items(), key=lambda kv: kv[1]):
        if var in closed or var in escaped:
            continue
        out.append(Finding(
            "TL004", mod.path, line, f"{quals_prefix}:{var}",
            f"fd-bearing local {var!r} is never closed on any path in "
            f"this function",
            "use `with`, close in a try/finally, or hand ownership to "
            "an object that closes it"))
    return out


# ---------------------------------------------------------------------------
# TL005: broad except that eats the evidence
# ---------------------------------------------------------------------------
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_LOG_RECEIVERS = {"log", "logger", "logging", "warnings", "traceback"}
_FLIGHT_METHODS = {"record", "dump"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(_last_segment(n) in ("Exception", "BaseException")
               for n in names)


def _handler_observes(handler: ast.ExceptHandler) -> bool:
    for node in _body_nodes(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name) and func.id in (
                "print", "_flight_incident", "fail", "perror"):
            return True
        if not isinstance(func, ast.Attribute):
            continue
        recv_node = func.value
        if isinstance(recv_node, ast.Call):     # get_flight().record(...)
            recv_node = recv_node.func
        recv = _last_segment(recv_node) or ""
        if func.attr in _LOG_METHODS and (
                recv in _LOG_RECEIVERS or recv.endswith("log")
                or recv.endswith("logger")):
            return True
        if func.attr in ("print_exc", "format_exc", "warn"):
            return True
        if func.attr in _FLIGHT_METHODS and "flight" in recv.lower():
            return True
        if func.attr == "_flight_incident":
            return True
        if func.attr == "inc" and "reject" in ast.dump(func).lower():
            return True
    return False


def check_broad_except(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    quals = _qualnames(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if _handler_observes(node):
            continue
        shown = "bare except" if node.type is None else \
            f"except {_last_segment(node.type) or '...'}"
        findings.append(Finding(
            "TL005", mod.path, node.lineno,
            quals.get(node, "<module>"),
            f"{shown} neither re-raises, logs, nor flight-records",
            "narrow the exception type, or log/flight-record before "
            "swallowing"))
    return findings


# ---------------------------------------------------------------------------
# TL006: proto wire additivity
# ---------------------------------------------------------------------------
_MSG_RE = re.compile(r"^\s*message\s+(\w+)\s*\{")
_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?[\w.<>, ]+?\s+(\w+)\s*=\s*(\d+)\s*;")
_RESERVED_RE = re.compile(r"^\s*reserved\s+([\d,\s]+);")


def parse_proto(path: str) -> dict[str, dict[str, int]]:
    """tony.proto -> {message: {field: number}}. A hand regex parser is
    enough: the control-plane proto is proto3 with flat messages and no
    nesting, and staying dependency-free matters more than generality."""
    messages: dict[str, dict[str, int]] = {}
    current: str | None = None
    depth = 0
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("//", 1)[0]
            m = _MSG_RE.match(line)
            if m and depth == 0:
                current = m.group(1)
                messages[current] = {}
            depth += line.count("{") - line.count("}")
            if depth <= 0:
                current = None
                depth = 0
                continue
            if current is None:
                continue
            fm = _FIELD_RE.match(line)
            if fm and not _MSG_RE.match(line):
                messages[current][fm.group(1)] = int(fm.group(2))
    return messages


def load_wire_manifest(path: str) -> dict[str, dict[str, int]] | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {msg: {k: int(v) for k, v in fields.items()}
            for msg, fields in doc.get("messages", {}).items()}


def write_wire_manifest(path: str, proto: dict[str, dict[str, int]],
                        old: dict[str, dict[str, int]] | None) -> None:
    """Merge-regenerate: new fields/messages are added; fields REMOVED
    from the proto are retained so their numbers stay released forever
    (reuse stays detectable). A renumber is refused upstream — it can
    never be laundered through regeneration."""
    merged: dict[str, dict[str, int]] = {}
    for msg in sorted(set(proto) | set(old or {})):
        fields = dict((old or {}).get(msg, {}))
        fields.update(proto.get(msg, {}))
        merged[msg] = dict(sorted(fields.items(), key=lambda kv: kv[1]))
    doc = {
        "version": 1,
        "note": "Released proto wire shape (message -> field -> number)."
                " Maintained by `python -m tony_tpu.devtools.lint"
                " --update-wire-manifest`; removed fields are retained"
                " so their numbers stay reserved. Hand-edit only to"
                " renumber a field that never shipped.",
        "messages": merged,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


def check_proto_additivity(root: str = REPO_ROOT) -> list[Finding]:
    proto_path = os.path.join(root, PROTO_FILE)
    manifest_path = os.path.join(root, WIRE_MANIFEST)
    rel = PROTO_FILE.replace(os.sep, "/")
    proto = parse_proto(proto_path)
    findings: list[Finding] = []
    # intra-proto: duplicate numbers are corrupt regardless of history
    for msg, fields in proto.items():
        by_num: dict[int, str] = {}
        for name, num in fields.items():
            if num in by_num:
                findings.append(Finding(
                    "TL006", rel, 1, f"{msg}.{name}",
                    f"field number {num} used by both "
                    f"{by_num[num]!r} and {name!r} in message {msg}",
                    "give the new field the next free number"))
            by_num[num] = name
    manifest = load_wire_manifest(manifest_path)
    if manifest is None:
        findings.append(Finding(
            "TL006", rel, 1, "wire_manifest",
            f"no committed wire manifest at "
            f"{WIRE_MANIFEST.replace(os.sep, '/')}",
            "run `python -m tony_tpu.devtools.lint "
            "--update-wire-manifest` and commit the result"))
        return findings
    for msg, released in manifest.items():
        live = proto.get(msg, {})
        live_by_num = {num: name for name, num in live.items()}
        for name, num in released.items():
            if name in live and live[name] != num:
                findings.append(Finding(
                    "TL006", rel, 1, f"{msg}.{name}",
                    f"released field {msg}.{name} renumbered "
                    f"{num} -> {live[name]} (breaks every shipped "
                    f"peer)",
                    "restore the released number; add a NEW field for "
                    "new semantics"))
            elif name not in live and num in live_by_num:
                findings.append(Finding(
                    "TL006", rel, 1, f"{msg}.{live_by_num[num]}",
                    f"field number {num} (released as {msg}.{name}) "
                    f"reused by new field {live_by_num[num]!r} — old "
                    f"peers will misparse it",
                    "give the new field the next free number; released "
                    "numbers are reserved forever"))
    return findings


# ---------------------------------------------------------------------------
# TL007: frame/op dispatch exhaustiveness
# ---------------------------------------------------------------------------
_FRAME_SOURCES = (
    os.path.join("tony_tpu", "serving", "protocol.py"),
    os.path.join("tony_tpu", "channels", "channel.py"),
)


def _frame_constants(root: str) -> dict[str, tuple[str, int]]:
    """{const_name: (defining relpath, lineno)}. protocol.py's set is
    the FRAME_NAMES dict's keys (authoritative); channel.py's is its
    top-level ``CH_* = <int>`` constants."""
    consts: dict[str, tuple[str, int]] = {}
    proto_mod = load_module(os.path.join(root, _FRAME_SOURCES[0]))
    if proto_mod is not None:
        for node in ast.walk(proto_mod.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "FRAME_NAMES"
                            for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if isinstance(k, ast.Name):
                        consts[k.id] = (proto_mod.path, k.lineno)
    chan_mod = load_module(os.path.join(root, _FRAME_SOURCES[1]))
    if chan_mod is not None:
        for node in chan_mod.tree.body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.startswith("CH_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)):
                consts[node.targets[0].id] = (chan_mod.path, node.lineno)
    return consts


def _dispatch_uses(mod: Module, names: set[str],
                   defining: dict[str, str]) -> set[str]:
    """Constants this module DISPATCHES on: used in a comparison,
    membership test, match-case, or as a dict key (dict keys only count
    outside the defining module — FRAME_NAMES itself is a name map, not
    a dispatch)."""
    used: set[str] = set()

    def note(node: ast.AST) -> None:
        seg = _last_segment(node)
        if seg in names:
            used.add(seg)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Compare):
            for sub in [node.left] + node.comparators:
                note(sub)
                if isinstance(sub, (ast.Tuple, ast.List, ast.Set)):
                    for e in sub.elts:
                        note(e)
        elif isinstance(node, ast.MatchValue):
            note(node.value)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is None:
                    continue
                seg = _last_segment(k)
                if seg in names and defining.get(seg) != mod.path:
                    used.add(seg)
    return used


def check_frame_exhaustiveness(root: str = REPO_ROOT,
                               modules: list[Module] | None = None
                               ) -> list[Finding]:
    consts = _frame_constants(root)
    if not consts:
        return []
    if modules is None:
        modules = scan_paths([os.path.join(root, "tony_tpu")])
    names = set(consts)
    defining = {n: p for n, (p, _) in consts.items()}
    used: set[str] = set()
    for mod in modules:
        if mod.path.startswith("tony_tpu/devtools/"):
            continue
        used |= _dispatch_uses(mod, names, defining)
    findings = []
    for name in sorted(names - used):
        path, line = consts[name]
        findings.append(Finding(
            "TL007", path, line, name,
            f"frame/op constant {name} has no dispatch arm anywhere "
            f"under tony_tpu/",
            "add the handler arm (or delete the dead constant)"))
    return findings


# ---------------------------------------------------------------------------
# TL008: observability bijections (metrics / events / config <-> docs)
# ---------------------------------------------------------------------------
#: string literals matching the series shape that are NOT metric series.
NON_SERIES = {"tony_pb2", "tony_tpu", "tony_src"}

_SERIES_LIT = re.compile(r"[\"'](tony_[a-z0-9_]+)[\"']")
_SERIES_FSTR = re.compile(r"f[\"'](tony_[a-z0-9_]*)\{")
#: ``f"{prefix}_seconds_total"`` — a registered-literal prefix plus a
#: dynamic suffix (metrics.py observe_phase_times style).
_SERIES_FSUFFIX = re.compile(r"f[\"']\{\w+\}(_[a-z0-9_]+)[\"']")
_DOC_SERIES = re.compile(r"(tony_[a-z0-9_]+)")
_EVENT_DECL = re.compile(r'^([A-Z][A-Z_]*) = "([A-Z][A-Z_]*)"',
                         flags=re.MULTILINE)
_DOC_EVENT_ROW = re.compile(r"^\|\s*`([A-Z][A-Z_]+)`\s*\|",
                            flags=re.MULTILINE)


def registered_series_names(root: str = REPO_ROOT
                            ) -> tuple[set[str], set[str], set[str]]:
    """(exact literals, truncated f-string prefixes, dynamic suffixes)
    of every ``tony_*`` series registered anywhere under tony_tpu/
    (devtools excluded — the linter's own fixtures are not the metrics
    plane)."""
    exact: set[str] = set()
    prefixes: set[str] = set()
    suffixes: set[str] = set()
    base = os.path.join(root, "tony_tpu")
    for dirpath, dirnames, files in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if os.path.basename(dirpath) == "devtools":
            dirnames[:] = []
            continue
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(dirpath, fn), encoding="utf-8").read()
            exact.update(_SERIES_LIT.findall(src))
            prefixes.update(_SERIES_FSTR.findall(src))
            suffixes.update(_SERIES_FSUFFIX.findall(src))
    return exact - NON_SERIES, prefixes, suffixes


def declared_event_types(root: str = REPO_ROOT) -> set[str]:
    """The SCREAMING_CASE ``NAME = "NAME"`` constants in
    events/events.py — the single registration point."""
    path = os.path.join(root, "tony_tpu", "events", "events.py")
    src = open(path, encoding="utf-8").read()
    return {value for name, value in _EVENT_DECL.findall(src)
            if name == value}


def config_key_constants(root: str = REPO_ROOT) -> tuple[set[str], dict]:
    """(*_KEY constant values, DEFAULTS dict) from conf/keys.py —
    imported, not parsed: keys.py is stdlib-only by design and the
    import keeps this in exact lockstep with the runtime."""
    from tony_tpu.conf import keys as K
    declared = {getattr(K, name) for name in dir(K)
                if name.endswith("_KEY")
                and isinstance(getattr(K, name), str)}
    return declared, dict(K.DEFAULTS)


def check_observability(root: str = REPO_ROOT,
                        facets: tuple[str, ...] = ("metrics", "events",
                                                   "config")
                        ) -> list[Finding]:
    findings: list[Finding] = []
    if "metrics" in facets:
        findings.extend(_check_metrics_docs(root))
    if "events" in facets:
        findings.extend(_check_events_docs(root))
    if "config" in facets:
        findings.extend(_check_config_docs(root))
    return findings


def _check_metrics_docs(root: str) -> list[Finding]:
    doc_rel = "docs/observability.md"
    doc = open(os.path.join(root, doc_rel), encoding="utf-8").read()
    exact, prefixes, suffixes = registered_series_names(root)
    findings = []
    if not exact:
        return [Finding("TL008", doc_rel, 1, "series-scan",
                        "series scan found nothing — the scanner "
                        "regressed", "fix registered_series_names")]
    # forward: every registered series (and every truncated f-string
    # prefix, e.g. tony_startup_) must appear in the docs table
    for name in sorted(set(n for n in exact if n not in doc)
                       | set(p for p in prefixes if p and p not in doc)):
        findings.append(Finding(
            "TL008", doc_rel, 1, name,
            f"series missing from docs/observability.md: {name}",
            "add a row to the metrics table (producer + meaning)"))
    # reverse: every series-shaped token the docs mention must be
    # registered somewhere — exactly, under a truncated f-prefix, as a
    # registered-prefix + dynamic-suffix composition, or as a docs
    # wildcard (``tony_serve_phase_*`` leaves a trailing-underscore
    # token) over real series
    doc_tokens = set(_DOC_SERIES.findall(doc)) - NON_SERIES
    for tok in sorted(doc_tokens):
        if tok in exact:
            continue
        if any(tok.startswith(p) for p in prefixes if p):
            continue
        if any(tok == lit + s for lit in exact for s in suffixes):
            continue                # f"{prefix}_seconds_total" style
        if any(lit.startswith(tok) or (lit + "_").startswith(tok)
               for lit in exact):
            continue                # docs wildcard like tony_serve_phase_*
        findings.append(Finding(
            "TL008", doc_rel, 1, tok,
            f"documented series {tok} is not registered anywhere under "
            f"tony_tpu/",
            "delete the stale docs row (or register the series)"))
    return findings


def _check_events_docs(root: str) -> list[Finding]:
    doc_rel = "docs/observability.md"
    doc = open(os.path.join(root, doc_rel), encoding="utf-8").read()
    types = declared_event_types(root)
    findings = []
    for t in sorted(x for x in types if x not in doc):
        findings.append(Finding(
            "TL008", doc_rel, 1, t,
            f"event types missing from docs/observability.md: {t}",
            "add a row to the jhist event-type table"))
    for t in sorted(set(_DOC_EVENT_ROW.findall(doc)) - types):
        findings.append(Finding(
            "TL008", doc_rel, 1, t,
            f"documented event type {t} is not declared in "
            f"events/events.py",
            "delete the stale docs row (or declare the constant)"))
    return findings


def _check_config_docs(root: str) -> list[Finding]:
    doc_rel = "docs/configuration.md"
    doc = open(os.path.join(root, doc_rel), encoding="utf-8").read()
    doc = doc.replace("\\|", "|")   # markdown-escaped | in defaults
    declared, defaults = config_key_constants(root)
    keys_rel = "tony_tpu/conf/keys.py"
    findings = []
    for k in sorted(declared - set(defaults)):
        findings.append(Finding(
            "TL008", keys_rel, 1, k,
            f"keys.py *_KEY constants and DEFAULTS registry out of "
            f"sync: missing defaults={{{k!r}}}",
            "add the key to DEFAULTS"))
    for k in sorted(set(defaults) - declared):
        findings.append(Finding(
            "TL008", keys_rel, 1, k,
            f"keys.py *_KEY constants and DEFAULTS registry out of "
            f"sync: orphan defaults={{{k!r}}}",
            "declare a *_KEY constant (or delete the default)"))
    for k in sorted(x for x in defaults if x not in doc):
        findings.append(Finding(
            "TL008", doc_rel, 1, k,
            f"undocumented config keys: [{k!r}]",
            "add a row to docs/configuration.md"))
    for suffix in ("instances", "memory", "vcores", "gpus", "tpus",
                   "tpu.topology", "resources"):
        if f"tony.<job>.{suffix}" not in doc:
            findings.append(Finding(
                "TL008", doc_rel, 1, f"tony.<job>.{suffix}",
                f"dynamic key tony.<job>.{suffix} undocumented",
                "add the dynamic-key row to docs/configuration.md"))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return list(doc.get("suppressions", []))


def apply_baseline(findings: list[Finding], suppressions: list[dict]
                   ) -> tuple[list[Finding], int, list[dict]]:
    """-> (surviving findings, suppressed count, stale entries)."""
    keys = {(s.get("checker"), s.get("path"), s.get("symbol"))
            for s in suppressions}
    hit: set[tuple] = set()
    out = []
    for f in findings:
        if f.key in keys:
            hit.add(f.key)
        else:
            out.append(f)
    stale = [s for s in suppressions
             if (s.get("checker"), s.get("path"), s.get("symbol"))
             not in hit]
    return out, len(findings) - len(out), stale


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
PER_FILE_CHECKERS = (check_blocking_under_lock, check_lock_discipline,
                     check_thread_hygiene, check_fd_hygiene,
                     check_broad_except)


def run_per_file_checkers(mod: Module) -> list[Finding]:
    out: list[Finding] = []
    for checker in PER_FILE_CHECKERS:
        out.extend(checker(mod))
    return out


def run(paths: list[str], *, root: str = REPO_ROOT,
        repo_checks: bool | None = None) -> list[Finding]:
    """All findings (un-baselined) for ``paths``. Repo-wide checkers
    (TL006/TL007/TL008) run when the scan covers the real tony_tpu
    package (auto), or per ``repo_checks``."""
    modules = scan_paths(paths)
    findings: list[Finding] = []
    for mod in modules:
        findings.extend(run_per_file_checkers(mod))
    if repo_checks is None:
        pkg = os.path.join(os.path.abspath(root), "tony_tpu") + os.sep
        repo_checks = any(m.abspath.startswith(pkg) for m in modules)
    if repo_checks:
        findings.extend(check_proto_additivity(root))
        findings.extend(check_frame_exhaustiveness(root, modules))
        findings.extend(check_observability(root))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.symbol))
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tony_tpu.devtools.lint",
        description="tonylint: AST invariant checker "
                    "(docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: tony_tpu/)")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT, DEFAULT_BASELINE),
                    help="suppression baseline JSON")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report pre-existing findings too")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--update-wire-manifest", action="store_true",
                    help="fold added proto fields into wire_manifest."
                         "json (renumbers/reuses still refuse)")
    args = ap.parse_args(argv)

    if args.update_wire_manifest:
        proto_path = os.path.join(REPO_ROOT, PROTO_FILE)
        manifest_path = os.path.join(REPO_ROOT, WIRE_MANIFEST)
        bad = [f for f in (check_proto_additivity(REPO_ROOT)
                           if os.path.exists(manifest_path) else [])
               if f.symbol != "wire_manifest"]
        if bad:
            for f in bad:
                print(f.render(), file=sys.stderr)
            print("tonylint: refusing to update the manifest over a "
                  "renumber/reuse — fix the proto first",
                  file=sys.stderr)
            return 1
        old = load_wire_manifest(manifest_path)
        write_wire_manifest(manifest_path, parse_proto(proto_path), old)
        print(f"tonylint: wire manifest updated at "
              f"{_relpath(manifest_path)}")
        return 0

    paths = args.paths or [os.path.join(REPO_ROOT, "tony_tpu")]
    findings = run(paths)
    if not args.no_baseline:
        findings, suppressed, stale = apply_baseline(
            findings, load_baseline(args.baseline))
        # an entry is only stale if its file was actually scanned —
        # linting a subset must not condemn the rest of the baseline
        scanned = [_relpath(p).rstrip("/") for p in paths]
        stale = [s for s in stale
                 if any(str(s.get("path", "")).startswith(sp)
                        for sp in scanned)]
        if stale:
            names = ", ".join(f"{s.get('checker')}:{s.get('symbol')}"
                              for s in stale[:8])
            print(f"tonylint: {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"match anything ({names}) — safe to delete",
                  file=sys.stderr)
    if args.as_json:
        print(json.dumps([dataclasses.asdict(f) for f in findings],
                         indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        print(f"tonylint: {len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''} "
              f"(suppress via {_relpath(args.baseline)} only for "
              f"pre-existing debt — the baseline only ratchets down)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
