"""Developer tooling for the tony_tpu tree itself.

Nothing here is imported by the runtime: ``devtools`` is the home of
``lint.py`` (tonylint — the AST-based invariant checker that gates the
repo's concurrency, wire, and observability disciplines; see
``docs/static-analysis.md``) and whatever future repo-hygiene tools ride
alongside. Dependency-free by design: stdlib only, importable on any
machine that can run the tests.
"""
