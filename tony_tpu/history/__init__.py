"""Job-history server: web UI + JSON API over the events layer.

Rebuild of the reference's tony-history-server Play application as a
dependency-free stdlib HTTP server (reference: tony-history-server/app/,
conf/routes:1-4)."""

from tony_tpu.history.server import (HistoryDirs, HistoryServer, TTLCache,
                                     migrate_finished, purge_expired)

__all__ = ["HistoryDirs", "HistoryServer", "TTLCache", "migrate_finished",
           "purge_expired"]
