"""History server: serves job metadata, per-job config, and event timelines.

Rebuild of the reference's tony-history-server (a Play 2.6 web app) as a
stdlib ``http.server`` application with the same observable behavior:

- routes ``/`` (jobs index), ``/jobs/<appId>`` (event timeline),
  ``/config/<appId>`` (frozen job config) — reference:
  tony-history-server/conf/routes:1-3 — plus a JSON API under ``/api/``
  for programmatic consumers (the reference exposes only HTML).
- on every index load, *finished* jobs are migrated from the intermediate
  dir into ``finished/yyyy/mm/dd`` keyed by completion date (reference:
  controllers/JobsMetadataPageController.java:49-72,95).
- parsed metadata / config / events are memoised in TTL caches keyed by
  app id (reference: cache/CacheWrapper.java — three Guava caches).
- required directories are created at startup (reference:
  hadoop/Requirements.java).
- files older than ``tony.history.retention-seconds`` are purged from the
  finished dir (retention is this build's addition; the reference leaves
  old jhist files forever).
"""

from __future__ import annotations

import argparse
import html
import json
import logging
import os
import threading
import time
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tony_tpu.conf import keys as K
from tony_tpu.conf.config import TonyConfig, parse_cli_confs
from tony_tpu.events import events as ev
from tony_tpu.runtime import goodput as goodput_mod
from tony_tpu.runtime import metrics as metrics_mod
from tony_tpu.storage import (StorageError, sdirname, sjoin, storage_for)

log = logging.getLogger(__name__)


# Re-exported for callers that think of them as part of the server's
# contract; the definitions live with the filename codec in the events layer
# so the coordinator/client share them without importing this HTTP module.
HistoryDirs = ev.HistoryDirs
config_file_name = ev.config_file_name


# ---------------------------------------------------------------------------
# Migration: intermediate -> finished/yyyy/mm/dd (reference:
# JobsMetadataPageController.java:49-72 moveIntermediateToFinished + :95).
# ---------------------------------------------------------------------------
def migrate_finished(dirs: HistoryDirs) -> list[str]:
    """Move completed jhist files (and their sibling config file) out of the
    intermediate dir into finished/yyyy/mm/dd. Returns the new paths."""
    moved = []
    store = storage_for(dirs.intermediate)
    if not store.isdir(dirs.intermediate):
        return moved
    names = store.listdir(dirs.intermediate)
    metas = {n: ev.JobMetadata.from_file_name(n) for n in names}
    # One pass over the snapshot; per-app ghost lists keep the cleanup O(n).
    inprogress_by_app: dict[str, list[str]] = {}
    for n, m in metas.items():
        if m and m.in_progress:
            inprogress_by_app.setdefault(m.app_id, []).append(n)
    for name in names:
        meta = metas[name]
        if meta is None or meta.in_progress or meta.completed_ms is None:
            continue
        when = datetime.fromtimestamp(meta.completed_ms / 1000, timezone.utc)
        dest_dir = sjoin(dirs.finished, f"{when.year:04d}",
                         f"{when.month:02d}", f"{when.day:02d}")
        store.makedirs(dest_dir)
        src = sjoin(dirs.intermediate, name)
        dest = sjoin(dest_dir, name)
        try:
            store.move(src, dest)
        except (FileNotFoundError, StorageError):
            continue    # a concurrent migration beat us to this file
        moved.append(dest)
        conf_src = sjoin(dirs.intermediate, config_file_name(meta.app_id))
        try:
            if store.exists(conf_src):
                store.move(conf_src,
                           sjoin(dest_dir, config_file_name(meta.app_id)))
        except (FileNotFoundError, StorageError):
            pass
        # A crashed earlier coordinator attempt can leave a stale
        # .jhist.inprogress for the same app id; once a completed jhist
        # exists it is authoritative — drop the ghost so it can't shadow
        # the real history.
        for other in inprogress_by_app.pop(meta.app_id, ()):
            try:
                store.remove(sjoin(dirs.intermediate, other))
            except (FileNotFoundError, StorageError):
                pass
    return moved


def purge_expired(dirs: HistoryDirs, retention_s: int) -> int:
    """Delete finished jhist/config files whose completion is older than the
    retention window. Returns the number of files removed."""
    if retention_s <= 0:
        return 0
    store = storage_for(dirs.finished)
    cutoff_ms = (time.time() - retention_s) * 1000
    removed = 0
    for path in ev.find_job_files(dirs.finished):
        meta = ev.JobMetadata.from_file_name(path)
        if meta and meta.completed_ms and meta.completed_ms < cutoff_ms:
            conf_path = sjoin(sdirname(path), config_file_name(meta.app_id))
            for p in (path, conf_path):
                try:
                    if store.exists(p):
                        store.remove(p)
                        removed += 1
                except (FileNotFoundError, StorageError):
                    pass    # concurrent purge or transient backend error
    return removed


# ---------------------------------------------------------------------------
# Caching (reference: cache/CacheWrapper.java — Guava caches for metadata,
# config, and events keyed by app id).
# ---------------------------------------------------------------------------
class TTLCache:
    def __init__(self, ttl_s: float = 30.0, max_entries: int = 1024) -> None:
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._data: dict[object, tuple[float, object]] = {}
        self._lock = threading.Lock()

    def get_or_load(self, key, loader):
        now = time.monotonic()
        with self._lock:
            hit = self._data.get(key)
            if hit and now - hit[0] < self.ttl_s:
                return hit[1]
        value = loader()
        if value is None:
            # Not-found is not worth remembering: a job appearing a moment
            # later must not keep 404ing for a full TTL.
            return None
        with self._lock:
            if len(self._data) >= self.max_entries:
                oldest = min(self._data, key=lambda k: self._data[k][0])
                del self._data[oldest]
            self._data[key] = (now, value)
        return value

    def invalidate_all(self) -> None:
        with self._lock:
            self._data.clear()


# ---------------------------------------------------------------------------
# The server.
# ---------------------------------------------------------------------------
_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>{title}</title><style>
body{{font-family:sans-serif;margin:2em;color:#222}}
table{{border-collapse:collapse;width:100%}}
th,td{{border:1px solid #ccc;padding:6px 10px;text-align:left;
font-size:14px}} th{{background:#f0f0f0}}
.SUCCEEDED{{color:#0a7d00}}.FAILED{{color:#b00020}}.KILLED{{color:#b00020}}
.RUNNING{{color:#8a6d00}} a{{color:#0645ad;text-decoration:none}}
h1{{font-size:20px}} pre{{background:#f7f7f7;padding:1em;overflow:auto}}
</style></head><body><h1>{title}</h1>{body}
<p><a href="/">&larr; all jobs</a></p></body></html>"""


def _fmt_ts(ms: int | None) -> str:
    if not ms:
        return "-"
    return datetime.fromtimestamp(ms / 1000, timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%S")


class HistoryServer:
    """Threaded HTTP server over the history directory tree.

    Routes (reference: tony-history-server/conf/routes:1-3):
      GET /                -> jobs-metadata index (triggers migration)
      GET /jobs/<appId>    -> per-job event timeline + latest metrics
      GET /config/<appId>  -> per-job frozen config
      GET /api/jobs, /api/jobs/<id>/events, /api/jobs/<id>/config -> JSON
      GET /metrics         -> Prometheus text exposition: live per-task
                              series from every RUNNING job's latest
                              METRICS_SNAPSHOT (heartbeat-shipped,
                              coordinator-aggregated), labeled
                              {job, task}, plus server-local gauges
      GET /api/jobs/<id>/metrics -> JSON replay of the job's
                              METRICS_SNAPSHOT events (works for
                              finished jobs purely from the jhist)
    """

    def __init__(self, conf: TonyConfig, port: int | None = None) -> None:
        self.conf = conf
        self.dirs = HistoryDirs.from_conf(conf)
        self.dirs.ensure()
        self.port = (port if port is not None
                     else conf.get_int(K.HISTORY_SERVER_PORT_KEY, 0))
        # Loopback by default: served job configs can embed env values and
        # paths. Exposing beyond the host (bind=0.0.0.0) is an explicit
        # choice, and pairs with bearer-token auth below (the reference's
        # auth analog is its keytab login, hadoop/Security.java).
        self.bind = conf.get(K.HISTORY_SERVER_BIND_KEY) or "127.0.0.1"
        token_file = conf.get(K.HISTORY_SERVER_TOKEN_FILE_KEY) or ""
        if token_file:
            with open(token_file, encoding="utf-8") as f:
                self.token = f.read().strip()
            if not self.token:
                raise ValueError(
                    f"history token file {token_file} is empty")
        else:
            self.token = conf.get(K.HISTORY_SERVER_TOKEN_KEY) or ""
        self.retention_s = conf.get_int(K.HISTORY_RETENTION_SECONDS_KEY, 0)
        self.metadata_cache = TTLCache(ttl_s=5.0)  # new jobs appear quickly
        self.events_cache = TTLCache()
        self.config_cache = TTLCache()
        # Serializes directory scans: concurrent index loads must not race
        # migrate_finished's move operations against each other.
        self._scan_lock = threading.Lock()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # path → uptime display string; finished jhist files are immutable
        self._uptime_by_path: dict[str, str] = {}

    # -- data access --------------------------------------------------------
    def list_jobs(self) -> list[dict]:
        """Cached directory scan — every route funnels through here, so the
        TTL bounds full-tree walks (reference: CacheWrapper's metadataCache)."""
        return self.metadata_cache.get_or_load("jobs", self._scan_jobs)

    def _scan_jobs(self) -> list[dict]:
        """Migrate finished jobs, purge expired, then list every valid jhist
        across intermediate + finished trees, newest first."""
        with self._scan_lock:
            return self._scan_jobs_locked()

    def _scan_jobs_locked(self) -> list[dict]:
        migrate_finished(self.dirs)
        purge_expired(self.dirs, self.retention_s)
        by_app: dict[str, dict] = {}
        for base in (self.dirs.intermediate, self.dirs.finished):
            for path in ev.find_job_files(base):
                meta = ev.JobMetadata.from_file_name(path)
                if meta is None:
                    continue
                job = {
                    "app_id": meta.app_id, "user": meta.user,
                    "started_ms": meta.started_ms,
                    "completed_ms": meta.completed_ms,
                    "status": meta.status or
                              ("RUNNING" if meta.in_progress else "UNKNOWN"),
                    "path": path}
                prev = by_app.get(meta.app_id)
                # A completed record is authoritative over a stale
                # .inprogress left by a crashed coordinator attempt.
                if prev is None or (prev["completed_ms"] is None
                                    and meta.completed_ms is not None):
                    by_app[meta.app_id] = job
        jobs = sorted(by_app.values(), key=lambda j: j["started_ms"],
                      reverse=True)
        # Evict uptime entries whose files were purged or migrated away so
        # the permanent cache tracks only live paths.
        live = {j["path"] for j in jobs}
        for stale in [p for p in self._uptime_by_path if p not in live]:
            del self._uptime_by_path[stale]
        return jobs

    def _find_job(self, app_id: str) -> dict | None:
        for job in self.list_jobs():
            if job["app_id"] == app_id:
                return job
        return None

    def _load_fresh_on_vanish(self, app_id: str, read_job):
        """Run ``read_job`` on the located job, re-scanning once if the file
        was migrated between lookup and read (cached paths can go stale the
        moment migrate_finished moves a file)."""
        for attempt in range(2):
            job = self._find_job(app_id)
            if job is None:
                return None
            try:
                return read_job(job)
            except (FileNotFoundError, StorageError):
                if attempt:
                    raise
                self.metadata_cache.invalidate_all()
        return None

    def job_events(self, app_id: str) -> list[ev.Event] | None:
        # In-progress files keep growing; the short TTL keeps the page fresh.
        return self.events_cache.get_or_load(
            app_id, lambda: self._load_fresh_on_vanish(
                app_id, lambda job: ev.parse_events(job["path"])))

    def job_config(self, app_id: str) -> dict | None:
        def read_config(job):
            conf_path = sjoin(sdirname(job["path"]),
                              config_file_name(app_id))
            store = storage_for(conf_path)
            if not store.exists(conf_path):
                return {}
            return TonyConfig.from_xml_bytes(
                store.read_bytes(conf_path)).as_dict()
        return self.config_cache.get_or_load(
            app_id, lambda: self._load_fresh_on_vanish(app_id, read_config))

    def job_uptime(self, job: dict) -> str:
        """Tracked-uptime fraction from the final event, as a display string
        ('-' while running / when absent). Finished jhist files are
        immutable, so the value is cached permanently per file path (a
        migration changes the path → one re-read); running jobs have no
        final event yet and are never parsed."""
        if job["completed_ms"] is None:
            return "-"
        path = job["path"]
        cached = self._uptime_by_path.get(path)
        if cached is not None:
            return cached
        result = "-"
        try:
            # jhist is JSON-lines with APPLICATION_FINISHED last: read only
            # the file tail instead of parsing N full event logs per index.
            tail = storage_for(path).read_tail(path, 65536).decode(
                "utf-8", errors="replace")
            for line in reversed(tail.splitlines()):
                if '"APPLICATION_FINISHED"' not in line:
                    continue
                payload = json.loads(line).get("payload", {})
                frac = (payload.get("metrics") or {}).get(
                    "tracked_uptime_fraction")
                if frac is not None:
                    result = f"{float(frac) * 100:.1f}%"
                break
        except Exception:
            # one malformed log must not 500 the whole index — but it
            # must leave evidence, or corrupt jhist files stay invisible
            log.warning("unreadable jhist tail for %s", path,
                        exc_info=True)
        self._uptime_by_path[path] = result
        return result

    # -- metrics -------------------------------------------------------------
    @staticmethod
    def _latest_metrics_snapshot(events: list[ev.Event]) -> ev.Event | None:
        for e in reversed(events):
            if e.event_type == ev.METRICS_SNAPSHOT:
                return e
        return None

    #: how much of a live jhist tail one scrape reads looking for the
    #: newest snapshot — comfortably holds many snapshot records; a
    #: fleet whose single snapshot outgrows this shows up as a missing
    #: job on /metrics, not an error
    _LIVE_TAIL_BYTES = 1 << 19

    def _latest_live_snapshot(self, job: dict) -> ev.Event | None:
        """Newest METRICS_SNAPSHOT of a RUNNING job, read from a bounded
        TAIL of its growing .inprogress file (the job_uptime idiom) —
        every scrape sees fresh values at O(tail) cost, instead of
        re-parsing an ever-growing file through the 30s events cache
        (which would both block handler threads on old jobs and serve
        30s-stale 'live' gauges against the 5s snapshot cadence)."""
        try:
            tail = storage_for(job["path"]).read_tail(
                job["path"], self._LIVE_TAIL_BYTES).decode(
                    "utf-8", errors="replace")
        except (OSError, StorageError):
            return None
        for line in reversed(tail.splitlines()):
            if '"METRICS_SNAPSHOT"' not in line:
                continue
            try:
                e = ev.Event.from_json(line)
            except (json.JSONDecodeError, KeyError):
                continue      # the tail window's partial first line
            if e.event_type == ev.METRICS_SNAPSHOT:
                return e
        return None

    #: snapshots returned in one /api/jobs/<id>/metrics response — a
    #: long-lived job at the 5s default cadence accumulates thousands of
    #: METRICS_SNAPSHOT events, and serializing all of them would block a
    #: handler thread on a multi-MB response; the newest ones are what a
    #: timeline consumer wants, and snapshot_count still reports the total
    MAX_METRICS_SNAPSHOTS = 200

    def job_metrics(self, app_id: str) -> dict | None:
        """JSON replay of a job's METRICS_SNAPSHOT events: the snapshot
        timeline (newest ``MAX_METRICS_SNAPSHOTS``, oldest-first;
        ``snapshot_count`` is the untruncated total) plus the latest
        per-task series — reconstructed purely from the jhist, so it
        works identically for running (.inprogress) and finished jobs."""
        events = self.job_events(app_id)
        if events is None:
            return None
        snaps = [e for e in events if e.event_type == ev.METRICS_SNAPSHOT]
        latest = snaps[-1] if snaps else None
        return {
            "app_id": app_id,
            "snapshot_count": len(snaps),
            "snapshots": [{"timestamp": e.timestamp,
                           "session_id": e.payload.get("session_id"),
                           "tasks": e.payload.get("tasks", {})}
                          for e in snaps[-self.MAX_METRICS_SNAPSHOTS:]],
            "tasks": (latest.payload.get("tasks", {}) if latest else {}),
        }

    #: GOODPUT windows returned in one /api/jobs/<id>/goodput response —
    #: same truncation rationale as MAX_METRICS_SNAPSHOTS; entries are
    #: cumulative, so the final window alone already carries the complete
    #: breakdown and truncating the timeline loses no attribution.
    MAX_GOODPUT_WINDOWS = 200

    def job_goodput(self, app_id: str) -> dict | None:
        """JSON replay of a job's GOODPUT events. ``tasks`` and
        ``fraction`` come VERBATIM from the last (cumulative) GOODPUT
        event, so the replayed breakdown is bit-exact against the live
        coordinator's final emission; ``windows`` is the truncated
        timeline for fraction-over-time consumers, and ``stragglers``
        the suspicion/clear verdicts the detector recorded."""
        events = self.job_events(app_id)
        if events is None:
            return None
        snaps = [e for e in events if e.event_type == ev.GOODPUT]
        latest = snaps[-1] if snaps else None
        stragglers = [
            {"timestamp": e.timestamp, "event_type": e.event_type,
             **(e.payload if isinstance(e.payload, dict) else {})}
            for e in events
            if e.event_type in (ev.STRAGGLER_SUSPECTED,
                                ev.STRAGGLER_CLEARED)]
        return {
            "app_id": app_id,
            "window_count": len(snaps),
            "windows": [{"timestamp": e.timestamp,
                         "session_id": e.payload.get("session_id"),
                         "fraction": e.payload.get("fraction"),
                         "tasks": e.payload.get("tasks", {})}
                        for e in snaps[-self.MAX_GOODPUT_WINDOWS:]],
            "tasks": (latest.payload.get("tasks", {}) if latest else {}),
            "fraction": (latest.payload.get("fraction")
                         if latest else None),
            "stragglers": stragglers,
        }

    def job_trace(self, app_id: str) -> dict | None:
        """Chrome Trace Event JSON (Perfetto / chrome://tracing
        loadable) reconstructed purely from the job's TRACE_SPAN jhist
        events — per-task clock offsets were already applied by the
        coordinator at export, so cross-process spans line up on one
        timeline. Works identically for running and finished jobs."""
        from tony_tpu.runtime import tracing
        events = self.job_events(app_id)
        if events is None:
            return None
        spans: list[dict] = []
        for e in events:
            if e.event_type != ev.TRACE_SPAN:
                continue
            batch = e.payload.get("spans", [])
            if not isinstance(batch, list):
                continue
            for s in batch:
                try:
                    spans.append(tracing.validate_span(s))
                except (ValueError, TypeError):
                    continue    # one bad span must not 404 the trace
        return tracing.to_chrome(spans)

    def render_prometheus(self) -> str:
        """Prometheus text exposition of LIVE series: every running job's
        latest coordinator-aggregated METRICS_SNAPSHOT (read from the
        flushed-per-event .inprogress jhist), each task's series labeled
        {job=<app_id>, task=<task_id>}, plus the server's own gauges.
        Names stay unique by construction (one latest snapshot per
        (job, task)); render_prometheus additionally drops any duplicate
        series defensively."""
        entries: list[tuple] = []
        jobs = self.list_jobs()
        running = 0
        for job in jobs:
            if job["status"] != "RUNNING":
                continue
            running += 1
            latest = self._latest_live_snapshot(job)
            if latest is None:
                continue
            tasks = latest.payload.get("tasks", {})
            if not isinstance(tasks, dict):
                continue
            for task_id, wire in sorted(tasks.items()):
                try:
                    metrics_mod.validate_wire(wire)
                except (ValueError, TypeError):
                    log.warning("skipping malformed snapshot for %s/%s",
                                job["app_id"], task_id)
                    continue
                entries.extend(metrics_mod.series_from_wire(
                    wire, {"job": job["app_id"], "task": task_id}))
        entries.append(("gauge", "tony_history_jobs",
                        {"state": "running"}, float(running),
                        "jobs known to the history server"))
        entries.append(("gauge", "tony_history_jobs",
                        {"state": "finished"}, float(len(jobs) - running),
                        "jobs known to the history server"))
        return metrics_mod.render_prometheus(entries)

    # -- html rendering ------------------------------------------------------
    def _render_index(self) -> str:
        rows = []
        for j in self.list_jobs():
            aid = html.escape(j["app_id"])
            rows.append(
                f"<tr><td><a href='/jobs/{aid}'>{aid}</a></td>"
                f"<td>{html.escape(j['user'])}</td>"
                f"<td>{_fmt_ts(j['started_ms'])}</td>"
                f"<td>{_fmt_ts(j['completed_ms'])}</td>"
                f"<td class='{j['status']}'>{j['status']}</td>"
                f"<td>{html.escape(self.job_uptime(j))}</td>"
                f"<td><a href='/config/{aid}'>config</a></td></tr>")
        body = ("<table><tr><th>Job</th><th>User</th><th>Started (UTC)"
                "</th><th>Completed (UTC)</th><th>Status</th>"
                "<th>Uptime</th><th></th>"
                "</tr>" + "".join(rows) + "</table>") if rows else \
            "<p>No jobs found.</p>"
        body = "<p><a href='/cluster'>cluster dashboard</a></p>" + body
        return _PAGE.format(title="TonY-TPU job history", body=body)

    # -- cluster dashboard ---------------------------------------------------
    _CLUSTER_EVENTS = (ev.JOB_QUEUED, ev.JOB_GRANTED, ev.JOB_PREEMPTED,
                       ev.JOB_COMPLETED)

    def cluster_state(self) -> dict:
        """Fold every cluster-daemon incarnation's jhist into one view of
        the daemon's lifetime: queued/running/completed jobs with
        per-job queue wait, warm/cold bring-up, and preemption counts.
        Replayable from jhist alone — the daemon itself may be gone."""
        daemons = [j for j in self.list_jobs()
                   if j["app_id"].startswith("cluster-daemon")]
        merged: list[ev.Event] = []
        for d in daemons:
            for e in (self.job_events(d["app_id"]) or []):
                if e.event_type in self._CLUSTER_EVENTS:
                    merged.append(e)
        merged.sort(key=lambda e: e.timestamp)
        jobs: dict[str, dict] = {}
        for e in merged:
            p = e.payload
            jid = str(p.get("job_id", ""))
            job = jobs.setdefault(jid, {
                "job_id": jid, "user": "", "priority": 0, "slices": 0,
                "state": "QUEUED", "queue_wait_s": 0.0, "warm": False,
                "warm_hits": 0, "preemptions": 0,
                "queued_ms": e.timestamp, "finished_ms": None})
            if e.event_type == ev.JOB_QUEUED:
                job.update(user=str(p.get("user", "")),
                           priority=int(p.get("priority", 0)),
                           slices=int(p.get("slices", 0)),
                           queued_ms=e.timestamp)
            elif e.event_type == ev.JOB_GRANTED:
                granted = p.get("slice_ids") or []
                warm_hits = int(p.get("warm_hits", 0))
                job["state"] = "RUNNING"
                job["queue_wait_s"] = round(
                    job["queue_wait_s"] + float(p.get("queue_wait_s", 0.0)),
                    6)
                job["warm_hits"] += warm_hits
                job["warm"] = bool(granted) and warm_hits == len(granted)
            elif e.event_type == ev.JOB_PREEMPTED:
                job["preemptions"] += 1
                if p.get("requeued"):
                    job["state"] = "QUEUED"
            elif e.event_type == ev.JOB_COMPLETED:
                job["state"] = str(p.get("status", "COMPLETED"))
                job["finished_ms"] = e.timestamp
        ordered = sorted(jobs.values(), key=lambda j: j["queued_ms"])
        states: dict[str, int] = {}
        for j in ordered:
            states[j["state"]] = states.get(j["state"], 0) + 1
        return {"daemons": [{"app_id": d["app_id"],
                             "status": d["status"]} for d in daemons],
                "states": states, "jobs": ordered}

    def _render_cluster(self) -> str:
        state = self.cluster_state()
        counts = " · ".join(f"{k}: {v}"
                            for k, v in sorted(state["states"].items()))
        rows = []
        for j in state["jobs"]:
            rows.append(
                f"<tr><td>{html.escape(j['job_id'])}</td>"
                f"<td>{html.escape(j['user'])}</td>"
                f"<td>{j['priority']}</td><td>{j['slices']}</td>"
                f"<td class='{html.escape(j['state'])}'>"
                f"{html.escape(j['state'])}</td>"
                f"<td>{j['queue_wait_s']:.3f}s</td>"
                f"<td>{'warm' if j['warm'] else 'cold'}</td>"
                f"<td>{j['preemptions']}</td></tr>")
        body = f"<p>{html.escape(counts) or 'No cluster jobs.'}</p>"
        if rows:
            body += ("<table><tr><th>Job</th><th>User</th><th>Priority"
                     "</th><th>Slices</th><th>State</th><th>Queue wait"
                     "</th><th>Bring-up</th><th>Preemptions</th></tr>"
                     + "".join(rows) + "</table>")
        body += ("<p><a href='/api/cluster'>JSON</a> · "
                 f"{len(state['daemons'])} daemon incarnation(s)</p>")
        return _PAGE.format(title="Cluster — jobs across the daemon's "
                                  "lifetime", body=body)

    def _render_events(self, app_id: str) -> str | None:
        events = self.job_events(app_id)
        if events is None:
            return None
        # METRICS_SNAPSHOT / LAUNCH / GOODPUT events render as their own
        # sections below, and TRACE_SPAN batches export through the trace
        # link — inlining each multi-task wire blob / span batch into the
        # timeline would bury the lifecycle events it exists to show.
        timeline = [e for e in events
                    if e.event_type not in (ev.METRICS_SNAPSHOT, ev.LAUNCH,
                                            ev.TRACE_SPAN, ev.GOODPUT)]
        rows = "".join(
            f"<tr><td>{_fmt_ts(e.timestamp)}</td>"
            f"<td>{html.escape(e.event_type)}</td>"
            f"<td><pre>{html.escape(json.dumps(e.payload, indent=1))}</pre>"
            f"</td></tr>" for e in timeline)
        body = ("<table><tr><th>Time (UTC)</th><th>Event</th><th>Payload</th>"
                "</tr>" + rows + "</table>") if timeline \
            else "<p>No events.</p>"
        if any(e.event_type == ev.TRACE_SPAN for e in events):
            n_spans = sum(len(e.payload.get("spans", []))
                          for e in events
                          if e.event_type == ev.TRACE_SPAN)
            body += (f"<p><a href='/api/jobs/{html.escape(app_id)}/trace'>"
                     f"Trace ({n_spans} spans, Chrome/Perfetto JSON)"
                     f"</a></p>")
        body += self._render_startup_section(events)
        body += self._render_goodput_section(events, app_id)
        body += self._render_metrics_section(events)
        return _PAGE.format(title=f"Events — {html.escape(app_id)}", body=body)

    @staticmethod
    def _render_startup_section(events: list[ev.Event]) -> str:
        """Per-gang bring-up walls from LAUNCH events: one row per timing
        record (gang, phase, wall seconds, cache-hit flag) so operators see
        where startup time went — and whether the content-addressed staging
        cache skipped the ship. Empty string when the job recorded none."""
        launches = [e for e in events if e.event_type == ev.LAUNCH]
        if not launches:
            return ""
        rows = []
        for e in launches:
            p = e.payload
            detail = "cache hit (ship skipped)" if p.get("cached") else (
                "reprovision" if p.get("reprovision") else "")
            try:
                seconds = f"{float(p.get('seconds', 0.0)):.3f}"
            except (TypeError, ValueError):
                # one malformed payload must not 500 the whole job page
                seconds = html.escape(str(p.get("seconds")))
            rows.append(
                f"<tr><td>{_fmt_ts(e.timestamp)}</td>"
                f"<td>{html.escape(str(p.get('gang', '')))}</td>"
                f"<td>{html.escape(str(p.get('phase', '')))}</td>"
                f"<td>{html.escape(str(p.get('task', '') or ''))}</td>"
                f"<td>{seconds}</td>"
                f"<td>{html.escape(detail)}</td></tr>")
        return ("<h1>Bring-up timeline</h1>"
                "<table><tr><th>Time (UTC)</th><th>Gang</th><th>Phase</th>"
                "<th>Task</th><th>Wall (s)</th><th></th></tr>"
                + "".join(rows) + "</table>")

    #: stacked-bar colors per ledger category — goodput (step) in green,
    #: input/IO waits in warm tones, framework walls in cool/neutral ones;
    #: unknown categories fall back to blue-grey
    _GOODPUT_COLORS = {
        "step": "#2e7d32", "data_wait": "#ef6c00", "checkpoint": "#1565c0",
        "eval": "#6a1b9a", "provision": "#9e9d24", "stage": "#00838f",
        "compile": "#c62828", "resync": "#ad1457", "recovery": "#4e342e",
        "idle": "#bdbdbd", "queue_wait": "#f9a825", "overhead": "#757575",
    }

    @classmethod
    def _render_goodput_section(cls, events: list[ev.Event],
                                app_id: str) -> str:
        """Headline goodput fraction + one stacked wall-clock bar per
        task from the LAST (cumulative) GOODPUT event: each segment's
        width is the share of that task's attributed wall spent in the
        category (executor ledger categories merged with the
        coordinator-attributed extras). Straggler verdicts already show
        in the event timeline; here only the counts are summarized.
        Empty string when the job shipped no ledger."""
        latest = None
        suspected = cleared = 0
        for e in events:
            if e.event_type == ev.GOODPUT:
                latest = e
            elif e.event_type == ev.STRAGGLER_SUSPECTED:
                suspected += 1
            elif e.event_type == ev.STRAGGLER_CLEARED:
                cleared += 1
        if latest is None:
            return ""
        p = latest.payload
        try:
            headline = f"{float(p.get('fraction')) * 100.0:.1f}%"
        except (TypeError, ValueError):
            headline = "n/a"
        tasks = p.get("tasks", {})
        rows = []
        for task_id in sorted(tasks if isinstance(tasks, dict) else ()):
            entry = tasks[task_id]
            if not isinstance(entry, dict):
                continue
            try:
                extra = entry.get("extra") or {}
                wall = max(0.0, float(entry.get("now", 0.0))
                           - float(entry.get("t0", 0.0))) \
                    + sum(float(s) for s in extra.values())
                cats: dict[str, float] = {}
                for src in (entry.get("cat") or {}, extra):
                    for c, s in src.items():
                        cats[str(c)] = cats.get(str(c), 0.0) + float(s)
            except (TypeError, ValueError, AttributeError):
                continue        # one malformed entry must not lose the page
            if wall <= 0:
                continue
            # ledger order first (stable bar layout across tasks), then
            # any categories this build doesn't know about
            order = [c for c in goodput_mod.CATEGORIES
                     if cats.get(c, 0.0) > 0]
            order += [c for c in sorted(cats)
                      if c not in goodput_mod.CATEGORIES and cats[c] > 0]
            segs = []
            for c in order:
                pct = 100.0 * cats[c] / wall
                color = cls._GOODPUT_COLORS.get(c, "#90a4ae")
                segs.append(
                    f"<div title='{html.escape(c)}: {cats[c]:.2f}s "
                    f"({pct:.1f}%)' style='display:inline-block;"
                    f"height:16px;width:{pct:.2f}%;"
                    f"background:{color}'></div>")
            step_pct = 100.0 * cats.get("step", 0.0) / wall
            rows.append(
                f"<tr><td>{html.escape(task_id)}</td>"
                f"<td style='width:60%'><div style='width:100%;"
                f"background:#eee;font-size:0;white-space:nowrap'>"
                + "".join(segs) + "</div></td>"
                f"<td>{wall:.1f}</td><td>{step_pct:.1f}%</td></tr>")
        legend = " ".join(
            f"<span style='white-space:nowrap'><span style='display:"
            f"inline-block;width:10px;height:10px;background:"
            f"{cls._GOODPUT_COLORS[c]}'></span> {c}</span>"
            for c in goodput_mod.CATEGORIES)
        body = (f"<h1>Goodput {headline}</h1>"
                f"<p>{legend}</p>"
                "<table><tr><th>Task</th><th>Wall breakdown</th>"
                "<th>Wall (s)</th><th>Goodput</th></tr>"
                + "".join(rows) + "</table>")
        if suspected:
            body += (f"<p>Stragglers: {suspected} suspected, "
                     f"{cleared} cleared (see timeline above).</p>")
        body += (f"<p><a href='/api/jobs/{html.escape(app_id)}/goodput'>"
                 "Goodput breakdown (JSON)</a></p>")
        return body

    def _render_metrics_section(self, events: list[ev.Event]) -> str:
        """Per-job metrics table from the LATEST snapshot: one row per
        (task, series) with counters/gauges as values and histograms as
        count/sum. Empty string when the job shipped no metrics."""
        latest = self._latest_metrics_snapshot(events)
        if latest is None:
            return ""
        rows = []
        tasks = latest.payload.get("tasks", {})
        for task_id in sorted(tasks if isinstance(tasks, dict) else ()):
            wire = tasks[task_id]
            try:
                metrics_mod.validate_wire(wire)
            except (ValueError, TypeError):
                continue
            for kind, name, labels, value, _ in \
                    metrics_mod.series_from_wire(wire):
                if kind == "histogram":
                    shown = (f"count={value['c']} "
                             f"sum={round(value['s'], 6)}")
                else:
                    shown = f"{round(float(value), 6):g}"
                label_txt = ",".join(f"{k}={v}"
                                     for k, v in sorted(labels.items()))
                rows.append(
                    f"<tr><td>{html.escape(task_id)}</td>"
                    f"<td>{html.escape(name)}</td>"
                    f"<td>{html.escape(label_txt)}</td>"
                    f"<td>{html.escape(shown)}</td></tr>")
        if not rows:
            return ""
        return ("<h1>Metrics (latest snapshot, "
                f"{_fmt_ts(latest.timestamp)})</h1>"
                "<table><tr><th>Task</th><th>Metric</th><th>Labels</th>"
                "<th>Value</th></tr>" + "".join(rows) + "</table>")

    def _render_config(self, app_id: str) -> str | None:
        conf = self.job_config(app_id)
        if conf is None:
            return None
        rows = "".join(
            f"<tr><td>{html.escape(k)}</td><td>{html.escape(v)}</td></tr>"
            for k, v in sorted(conf.items()))
        body = ("<table><tr><th>Key</th><th>Value</th></tr>" + rows +
                "</table>") if conf else "<p>No config file recorded.</p>"
        return _PAGE.format(title=f"Config — {html.escape(app_id)}", body=body)

    # -- http plumbing -------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to logging, not stderr
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", f"{ctype}; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, obj, code: int = 200) -> None:
                self._send(code, json.dumps(obj, indent=1), "application/json")

            def _authorized(self) -> bool:
                """Bearer-token check (constant-time). /healthz stays open
                so load balancers can probe without the secret."""
                if not server.token:
                    return True
                import hmac
                header = self.headers.get("Authorization", "")
                scheme, _, presented = header.partition(" ")
                return (scheme.lower() == "bearer"
                        and hmac.compare_digest(presented.strip(),
                                                server.token))

            def do_GET(self):  # noqa: N802 (stdlib API name)
                # Match on the path only — '/api/jobs?limit=5' must route.
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path != "/healthz" and not self._authorized():
                        self.send_response(401)
                        self.send_header("WWW-Authenticate", "Bearer")
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    self._route(path)
                except BrokenPipeError:
                    pass
                except Exception:  # pragma: no cover - defensive 500
                    log.exception("history server error on %s", path)
                    self._send(500, "internal error", "text/plain")

            def _route(self, path: str) -> None:
                if path == "/":
                    self._send(200, server._render_index(), "text/html")
                elif path == "/cluster":
                    self._send(200, server._render_cluster(), "text/html")
                elif path == "/api/cluster":
                    self._json(server.cluster_state())
                elif path.startswith("/jobs/"):
                    page = server._render_events(path[len("/jobs/"):])
                    self._not_found() if page is None else \
                        self._send(200, page, "text/html")
                elif path.startswith("/config/"):
                    page = server._render_config(path[len("/config/"):])
                    self._not_found() if page is None else \
                        self._send(200, page, "text/html")
                elif path == "/metrics":
                    self._send(200, server.render_prometheus(),
                               "text/plain; version=0.0.4")
                elif path == "/api/jobs":
                    self._json(server.list_jobs())
                elif path.startswith("/api/jobs/") and \
                        path.endswith("/metrics"):
                    app_id = path[len("/api/jobs/"):-len("/metrics")]
                    m = server.job_metrics(app_id)
                    self._not_found() if m is None else self._json(m)
                elif path.startswith("/api/jobs/") and \
                        path.endswith("/goodput"):
                    app_id = path[len("/api/jobs/"):-len("/goodput")]
                    g = server.job_goodput(app_id)
                    self._not_found() if g is None else self._json(g)
                elif path.startswith("/api/jobs/") and \
                        path.endswith("/trace"):
                    app_id = path[len("/api/jobs/"):-len("/trace")]
                    t = server.job_trace(app_id)
                    self._not_found() if t is None else self._json(t)
                elif path.startswith("/api/jobs/") and \
                        path.endswith("/events"):
                    app_id = path[len("/api/jobs/"):-len("/events")]
                    events = server.job_events(app_id)
                    self._not_found() if events is None else self._json(
                        [{"event_type": e.event_type, "payload": e.payload,
                          "timestamp": e.timestamp} for e in events])
                elif path.startswith("/api/jobs/") and \
                        path.endswith("/config"):
                    app_id = path[len("/api/jobs/"):-len("/config")]
                    conf = server.job_config(app_id)
                    self._not_found() if conf is None else self._json(conf)
                elif path == "/healthz":
                    self._send(200, "ok", "text/plain")
                else:
                    self._not_found()

            def _not_found(self) -> None:
                self._send(404, _PAGE.format(
                    title="Not found", body="<p>Unknown job or path.</p>"),
                    "text/html")

        return Handler

    def start(self) -> int:
        """Bind + serve on a background thread. Returns the bound port."""
        if self.bind not in ("127.0.0.1", "localhost", "::1") \
                and not self.token:
            log.warning(
                "history server binding %s WITHOUT auth — job configs may "
                "embed env/paths; set %s (or .token-file) to require a "
                "bearer token", self.bind, K.HISTORY_SERVER_TOKEN_KEY)
        # HTTPS (the reference's tony.https.* keystore analog,
        # TonyConfigurationKeys.java:55-68): PEM cert + key paths → wrap
        # the listening socket; plaintext requests fail the handshake.
        # Validation and context construction happen BEFORE the server
        # binds — a config error must not leak a bound socket whose
        # stop() would then hang in shutdown().
        scheme = "http"
        ctx = None
        cert = self.conf.get(K.HISTORY_SERVER_TLS_CERT_KEY) or ""
        key = self.conf.get(K.HISTORY_SERVER_TLS_KEY_KEY) or ""
        if bool(cert) != bool(key):
            raise ValueError(
                "history server TLS needs BOTH tls-cert and tls-key "
                f"(got cert={cert!r}, key={key!r})")
        if cert:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=cert, keyfile=key)
            scheme = "https"
        self._httpd = ThreadingHTTPServer((self.bind, self.port),
                                          self._make_handler())
        if ctx is not None:
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="tony-history-server",
                                        daemon=True)
        self._thread.start()
        log.info("history server on %s://%s:%d (auth=%s intermediate=%s "
                 "finished=%s)", scheme, self.bind, self.port,
                 "bearer" if self.token else "off", self.dirs.intermediate,
                 self.dirs.finished)
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def main(argv: list[str] | None = None) -> int:
    """Standalone launcher (reference: startTHS.sh reads tony-site.xml and
    boots the Play app; here: ``python -m tony_tpu.history.server``)."""
    parser = argparse.ArgumentParser(prog="tony-history-server")
    parser.add_argument("--conf_file", help="tony.xml / k=v config file")
    parser.add_argument("--conf", action="append", default=[],
                        help="config override key=value (repeatable)")
    parser.add_argument("--port", type=int, default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s: "
                               "%(message)s")
    conf = TonyConfig.load(args.conf_file,
                           cli_overrides=parse_cli_confs(args.conf))
    server = HistoryServer(conf, port=args.port)
    server.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
