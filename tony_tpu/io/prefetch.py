"""Device-prefetched training input pipeline: decode-ahead + H2D overlap.

PR 1 removed host/device serialization from the *serve* loop; this module
removes it from the *train* path. Without it every step pays reader decode,
``jax.make_array_from_process_local_data`` assembly, and the host-to-device
copy inline between step dispatches — the training loop is input-bound the
moment decode cost is nonzero (TF-Replicator's overlapped host input
pipelines and Podracer's decoupled host/device architecture both hinge on
exactly this overlap; see PAPERS.md).

:class:`DevicePrefetcher` runs a background producer thread that pulls host
batches from a source (``reader_epochs`` over the sharded data-feed layer,
or any iterable), assembles them into **global sharded jax.Arrays**
(``jax.make_array_from_process_local_data`` against the train step's batch
sharding) or ``jax.device_put``s them, and parks them in a bounded queue —
so the H2D transfer of batch N+1 overlaps the device compute of batch N.

Contract (each clause is test-pinned in tests/test_prefetch.py):

- **clean shutdown** — ``close()`` stops the producer, drains the queue
  (a put-blocked producer can never deadlock a closing consumer), joins
  the thread, and drops the queue reference so parked device batches are
  GC-able; a prefetcher dropped without ``close()`` is released by a
  ``weakref`` finalizer (the reader's finalizer discipline);
- **exception propagation** — a producer error (decode failure, source
  bug) re-raises in the consumer with its ORIGINAL traceback, never
  swallowed in a daemon thread;
- **deterministic epochs** — an epochal source is called as
  ``source(epoch)``; :func:`reader_epochs` seeds each epoch's reshuffle
  with ``seed + epoch``, so a restarted job replays the same stream;
- **consistent shapes** — every produced batch must match the first
  batch's tree structure and leaf shapes/dtypes; a mismatch raises
  :class:`PrefetchShapeError` instead of silently retracing the jitted
  train step (the train-side analog of serve's retrace guard).
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import weakref
from typing import Any, Callable, Iterable, Iterator

log = logging.getLogger(__name__)

_SENTINEL = object()
_THREAD_SEQ = itertools.count()


class PrefetchShapeError(RuntimeError):
    """A produced batch's structure or leaf shapes/dtypes differ from the
    first batch's — feeding it would silently retrace the jitted step."""


def _tree_spec(tree: Any) -> tuple:
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    return (str(treedef),
            tuple((tuple(getattr(l, "shape", ())),
                   str(getattr(l, "dtype", type(l).__name__)))
                  for l in leaves))


def _assemble(batch: Any, sharding) -> Any:
    """Host pytree → device pytree, ON THE PRODUCER THREAD (this is the
    H2D copy the overlap hides). With a sharding every leaf assembles as
    a global sharded array from this process's local shard (the
    multi-host feeding recipe — ``train.global_batch``); without one,
    ``device_put`` to the default device (single-process feeds)."""
    import jax
    if sharding is None:
        return jax.tree.map(jax.device_put, batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        batch)


def _iterate(source, epochs: int | None) -> Iterator[Any]:
    """The one epochal-iteration contract, shared by the producer thread
    and :func:`synchronous_batches`: a callable source is cycled
    ``source(0), source(1), …`` (bounded by ``epochs``), a plain iterable
    is a single pass, and an empty epoch raises instead of spinning
    forever under ``itertools.count()``."""
    epochal = callable(source)
    epoch_iter: Iterable[int] = (
        (range(epochs) if epochs is not None else itertools.count())
        if epochal else (0,))
    for epoch in epoch_iter:
        produced = 0
        for host_batch in (source(epoch) if epochal else source):
            produced += 1
            yield host_batch
        if epochal and produced == 0:
            raise ValueError(
                f"prefetch source yielded no batches for epoch {epoch} "
                f"— nothing to train on")


def synchronous_batches(source, sharding=None,
                        epochs: int | None = None) -> Iterator[Any]:
    """The prefetcher's stream WITHOUT the producer thread: decode +
    assembly + H2D inline on the caller's critical path. The A/B
    contrast arm (``train_lm.py --prefetch_depth 0``) — same source
    protocol, same epochal cycling and empty-epoch guard, so the two
    feeds differ only in overlap."""
    for host_batch in _iterate(source, epochs):
        yield _assemble(host_batch, sharding)


def _producer(source, epochs, sharding, q, stop, error_box) -> None:
    """Producer body (module-level: must NOT reference the prefetcher —
    it would pin it against its finalizer). Any error lands in
    ``error_box`` and re-raises in the consumer. The trailing sentinel is
    best-effort with a bounded loop; consumers use timeout-gets that
    re-check ``stop``, so a missing sentinel cannot deadlock them."""
    spec = None
    try:
        for host_batch in _iterate(source, epochs):
            if stop.is_set():
                return
            batch = _assemble(host_batch, sharding)
            got = _tree_spec(batch)
            if spec is None:
                spec = got
            elif got != spec:
                raise PrefetchShapeError(
                    f"batch shape changed mid-stream: first batch was "
                    f"{spec}, got {got} — the jitted train step would "
                    f"retrace; pad or drop the odd batch")
            while not stop.is_set():
                try:
                    q.put(batch, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if stop.is_set():
                return
    except BaseException as e:           # noqa: BLE001 — box EVERYTHING;
        error_box.append(e)              # the consumer re-raises it
    finally:
        for _ in range(50):
            try:
                q.put(_SENTINEL, timeout=0.1)
                break
            except queue.Full:
                if stop.is_set():
                    break


def _release(stop, q) -> None:
    """Finalizer for prefetchers dropped without close(): unblock the
    producer (it exits its put loop once ``stop`` is set and the queue
    has room)."""
    stop.set()
    try:
        q.get_nowait()
    except Exception:
        pass


class DevicePrefetcher:
    """Iterator of device-resident batches, assembled ``depth`` ahead.

    ``source`` is either a plain iterable of host-batch pytrees (one
    pass), or a callable ``epoch -> iterable`` (epochal mode: called with
    0, 1, 2, … so the source can reshuffle deterministically per epoch —
    see :func:`reader_epochs`; ``epochs`` bounds the count, None cycles
    forever). ``sharding`` is the train step's batch
    :class:`~jax.sharding.NamedSharding` (``train.batch_sharding``),
    applied to every leaf's leading dims; None means plain
    ``device_put``.

    ``depth`` bounds the queue: each slot parks one full global batch of
    DEVICE memory, so 2 (one being consumed + one in flight) is right
    unless per-batch decode cost is highly variable.

    Usage::

        with DevicePrefetcher(epoch_fn, sharding=b_sharding) as batches:
            state, metrics = run_training(step_fn, state, batches, steps)
    """

    def __init__(self, source: Iterable | Callable[[int], Iterable],
                 sharding=None, depth: int = 2,
                 epochs: int | None = None) -> None:
        self._q: queue.Queue | None = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        #: one-slot box the producer stores its exception into
        self._error_box: list = []
        self._done = False
        self._thread = threading.Thread(
            target=_producer,
            args=(source, epochs, sharding, self._q, self._stop,
                  self._error_box),
            name=f"tony-datafeed-device-{next(_THREAD_SEQ)}", daemon=True)
        self._thread.start()
        self._finalizer = weakref.finalize(
            self, _release, self._stop, self._q)

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        if self._q is None:
            raise RuntimeError("DevicePrefetcher is closed")
        while True:
            try:
                # timeout + stop re-check: a cross-thread close() may
                # retire the producer before its sentinel lands
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    raise RuntimeError("DevicePrefetcher is closed")
                if not self._thread.is_alive():
                    # The producer may have parked its last batch(es) +
                    # sentinel and exited INSIDE our timeout window — drain
                    # before concluding, or a finite epoch silently loses
                    # its tail.
                    try:
                        item = self._q.get_nowait()
                    except queue.Empty:
                        self._done = True
                        if self._error_box:
                            raise self._error_box.pop()
                        raise StopIteration
                    if item is _SENTINEL:
                        self._done = True
                        if self._error_box:
                            raise self._error_box.pop()
                        raise StopIteration
                    return item
                continue
            if item is _SENTINEL:
                self._done = True
                if self._error_box:
                    # the exception object carries the producer's original
                    # traceback; re-raising here preserves it
                    raise self._error_box.pop()
                raise StopIteration
            return item

    def close(self) -> None:
        """Stop the producer and release everything it parked. Never
        blocks on a full queue (close-during-full-queue is test-pinned),
        never leaves a live thread behind on the normal path."""
        self._stop.set()
        q = self._q
        if q is not None:
            while True:                   # unblock a put() on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        if self._thread.is_alive():
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                log.warning("device-prefetch thread did not exit; dropping "
                            "its queue (daemon thread dies with the process)")
        if q is not None:
            while True:                   # items put between drain and exit
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        # Drop the queue reference (and the finalizer's) so parked device
        # batches are GC-able even if the thread is wedged in the source.
        self._finalizer.detach()
        self._q = None
        self._done = True

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def reader_epochs(paths: list[str], batch_size_per_process: int, dtype,
                  row_shape: tuple[int, ...], *, shuffle: bool = True,
                  seed: int = 0, process_index: int | None = None,
                  process_count: int | None = None,
                  ) -> tuple[Callable[[int], Iterator], int]:
    """Epochal host-batch source over the sharded data-feed layer.

    Returns ``(epoch_fn, batches_per_epoch)``: ``epoch_fn(epoch)`` yields
    this process's LOCAL ``[batch, *row_shape]`` ndarrays for one pass
    over its byte-range split, reshuffled deterministically per epoch
    (reader seed = ``seed + epoch`` — a resumed attempt replays the same
    stream). Every process yields the SAME ``batches_per_epoch`` — the
    minimum over all processes' full-batch counts, computed from file
    sizes with no communication (``jax_feed.global_batches``' equal-count
    guarantee) — so the jitted-step loop cannot deadlock multi-host.
    """
    from tony_tpu.io.jax_feed import array_batches, record_size_for
    from tony_tpu.io.reader import FileSplitReader
    from tony_tpu.io.split import full_records_in_split
    from tony_tpu.storage import ssize

    if process_index is None or process_count is None:
        import jax
        pid = jax.process_index() if process_index is None else process_index
        pcount = (jax.process_count() if process_count is None
                  else process_count)
    else:
        pid, pcount = process_index, process_count
    record_size = record_size_for(dtype, row_shape)
    sizes = [ssize(p) for p in paths]
    per_epoch = min(
        full_records_in_split(paths, i, pcount, record_size, sizes=sizes)
        // batch_size_per_process
        for i in range(pcount))

    def epoch_fn(epoch: int) -> Iterator:
        reader = FileSplitReader(
            paths, task_index=pid, task_num=pcount,
            record_size=record_size, shuffle=shuffle, seed=seed + epoch,
            sizes=sizes)
        try:
            it = array_batches(reader, batch_size_per_process, dtype,
                               row_shape)
            for _ in range(per_epoch):
                yield next(it)
        finally:
            reader.close()

    return epoch_fn, per_epoch


def elastic_epochs(paths: list[str], global_batch: int, dtype,
                   row_shape: tuple[int, ...], *, shuffle: bool = True,
                   seed: int = 0, start_step: int = 0,
                   process_index: int | None = None,
                   process_count: int | None = None,
                   ) -> tuple[Iterator, int]:
    """World-size-invariant epochal stream for ELASTIC training.

    :func:`reader_epochs` partitions by byte range, so the per-process
    stream depends on the process COUNT — after an elastic shrink the
    survivors' splits reshuffle and a mid-epoch resume would silently
    drop some examples and double-feed others. This source instead fixes
    ONE canonical stream — the single-reader pass over all files
    (``task_num=1``), reshuffled with ``seed + epoch``, chunked into
    ``global_batch``-row global batches — and hands process ``p`` of
    ``P`` rows ``[p*B/P, (p+1)*B/P)`` of every global batch. The global
    batch at step ``s`` is therefore IDENTICAL at any world size: a
    training run that shrinks from N to N-1 processes (or grows back)
    replays exactly the canonical sequence, which is what pins loss-curve
    continuity across elastic transitions.

    ``start_step`` aligns the stream with a restored checkpoint: the
    first yielded batch is the one for global step ``start_step``
    (``epoch = s // batches_per_epoch``, position ``s %
    batches_per_epoch``; the skipped prefix of the resume epoch is
    decoded and discarded — shuffled streams have no seek).

    Returns ``(iterator, batches_per_epoch)``; the iterator is infinite
    (cycles epochs) and yields this process's LOCAL ``[B/P, *row_shape]``
    ndarray slice. Tradeoff vs ``reader_epochs``: every process reads
    the WHOLE dataset (the invariance cost) — right for elastic jobs
    whose per-epoch bytes fit host IO comfortably; keep the byte-range
    splits for fixed-gang jobs with very large inputs.
    """
    import itertools as _it

    from tony_tpu.io.jax_feed import array_batches, record_size_for
    from tony_tpu.io.reader import FileSplitReader
    from tony_tpu.io.split import full_records_in_split
    from tony_tpu.storage import ssize

    if process_index is None or process_count is None:
        import jax
        pid = jax.process_index() if process_index is None else process_index
        pcount = (jax.process_count() if process_count is None
                  else process_count)
    else:
        pid, pcount = process_index, process_count
    if global_batch % pcount != 0:
        raise ValueError(
            f"elastic_epochs: global_batch={global_batch} must divide "
            f"evenly over {pcount} process(es) — choose a global batch "
            f"divisible by every world size the job can shrink to")
    local = global_batch // pcount
    record_size = record_size_for(dtype, row_shape)
    sizes = [ssize(p) for p in paths]
    per_epoch = (full_records_in_split(paths, 0, 1, record_size,
                                       sizes=sizes) // global_batch)
    if per_epoch == 0:
        raise ValueError(
            f"data files hold fewer than one global batch "
            f"(global_batch={global_batch}) — nothing to train on")

    def stream() -> Iterator:
        step = start_step
        for epoch in _it.count(start_step // per_epoch):
            reader = FileSplitReader(
                paths, task_index=0, task_num=1, record_size=record_size,
                shuffle=shuffle, seed=seed + epoch, sizes=sizes)
            try:
                it = array_batches(reader, global_batch, dtype, row_shape)
                skip = step % per_epoch
                for pos in range(per_epoch):
                    g = next(it)
                    if pos < skip:
                        continue    # decoded + discarded resume prefix
                    step += 1
                    yield g[pid * local:(pid + 1) * local]
            finally:
                reader.close()

    return stream(), per_epoch
