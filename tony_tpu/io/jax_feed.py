"""Bridge from the per-task data feed into sharded ``jax.Array``s.

The reference hands batches to user TF/PyTorch code over py4j and stops there
(reference: HdfsAvroFileSplitReader.java:103-133 — bytes / in-mem file /
local-spill delivery). On TPU the natural delivery target is a *global*
``jax.Array``: each process reads only its split (FileSplitReader) and
``jax.make_array_from_process_local_data`` assembles the global batch over
the mesh's data axes — the SPMD-native version of "three batch delivery
modes" (SURVEY.md §7 step 9).
"""

from __future__ import annotations

import logging
from typing import Iterator

import numpy as np

from tony_tpu.io.reader import FileSplitReader
from tony_tpu.storage import ssize
from tony_tpu.io.split import full_records_in_split

log = logging.getLogger(__name__)


def records_to_array(records: list[bytes], dtype,
                     row_shape: tuple[int, ...]) -> np.ndarray:
    """Decode fixed-size records into a [batch, *row_shape] ndarray."""
    if not records:
        return np.empty((0, *row_shape), dtype=dtype)
    flat = np.frombuffer(b"".join(records), dtype=dtype)
    return flat.reshape(len(records), *row_shape)


def record_size_for(dtype, row_shape: tuple[int, ...]) -> int:
    """Bytes per fixed-size record holding one ``dtype``-typed row."""
    return int(np.dtype(dtype).itemsize * np.prod(row_shape, dtype=np.int64))


def array_batches(reader: FileSplitReader, batch_size: int, dtype,
                  row_shape: tuple[int, ...],
                  drop_remainder: bool = True) -> Iterator[np.ndarray]:
    """Iterate the reader's split as fixed-size [batch, *row_shape] arrays.

    Short tail records (a file whose size is not a record multiple) are
    dropped — they cannot form a full row. The drop warning fires once per
    READER (flagged on the reader object), not once per call site: a
    reader consumed through several ``array_batches`` calls — the spill /
    prefetch mixed-delivery pattern — still reports its short tails
    exactly once.
    """
    rec_bytes = record_size_for(dtype, row_shape)
    exhausted = False
    while not exhausted:
        # Keep pulling until we hold batch_size FULL records or the reader is
        # dry — a short tail record filtered mid-stream must not end the
        # iteration while later files still have data.
        full: list[bytes] = []
        while len(full) < batch_size:
            records = reader.next_batch(batch_size - len(full))
            if not records:
                exhausted = True
                break
            kept = [r for r in records if len(r) == rec_bytes]
            if len(kept) < len(records) and not getattr(
                    reader, "_short_tail_warned", False):
                reader._short_tail_warned = True
                log.warning("dropping %d short tail record(s) (< %d bytes)",
                            len(records) - len(kept), rec_bytes)
            full.extend(kept)
        if not full:
            return
        if len(full) < batch_size and drop_remainder:
            return
        yield records_to_array(full, dtype, row_shape)


def to_global_array(local_batch: np.ndarray, mesh,
                    batch_axes: tuple[str, ...] = ("dp",)):
    """Assemble each process's local batch into one global jax.Array sharded
    along the mesh's data axes (leading dim), replicated elsewhere."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    if batch_axes and not axes:
        # Silent fallback to P(None) would REPLICATE per-process-distinct
        # data — garbage "global" batches on multi-host. Demand an explicit
        # batch_axes=() for intentional replication.
        raise ValueError(
            f"none of batch_axes {batch_axes} exist in mesh axes "
            f"{mesh.axis_names}; pass batch_axes=() for replication")
    sharding = NamedSharding(mesh, P(axes if axes else None))
    return jax.make_array_from_process_local_data(sharding, local_batch)


def global_batches(paths: list[str], batch_size_per_process: int, dtype,
                   row_shape: tuple[int, ...], mesh,
                   batch_axes: tuple[str, ...] = ("dp",),
                   shuffle: bool = False, seed: int = 0,
                   process_index: int | None = None,
                   process_count: int | None = None):
    """End-to-end feed: split files across processes, read + decode locally,
    assemble global sharded batches. The one-call path a training loop uses::

        for batch in global_batches(paths, 32, np.float32, (28, 28), mesh):
            state, metrics = train_step(state, batch)

    Every process yields the SAME number of batches — the minimum over all
    processes' full-batch counts, computed deterministically from file sizes
    (no communication) — so the jitted-step loop cannot deadlock multi-host
    when splits land unequal record counts.
    """
    import jax

    pid = jax.process_index() if process_index is None else process_index
    pcount = jax.process_count() if process_count is None else process_count
    record_size = record_size_for(dtype, row_shape)
    sizes = [ssize(p) for p in paths]
    num_batches = min(
        full_records_in_split(paths, i, pcount, record_size, sizes=sizes)
        // batch_size_per_process
        for i in range(pcount))
    reader = FileSplitReader(
        paths, task_index=pid, task_num=pcount, record_size=record_size,
        shuffle=shuffle, seed=seed, sizes=sizes)
    try:
        it = array_batches(reader, batch_size_per_process, dtype, row_shape)
        for _ in range(num_batches):
            yield to_global_array(next(it), mesh, batch_axes)
    finally:
        reader.close()
