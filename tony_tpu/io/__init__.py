"""Data-feed IO: sharded file-split reading into sharded jax.Arrays.

TPU-native rebuild of the reference's data-feed layer (reference: tony-core/
src/main/java/com/linkedin/tony/io/HdfsAvroFileSplitReader.java, reached from
Python over py4j per TaskExecutor.java:281). Components:

  split      — global contiguous byte-range split math (reference :286-297)
  framed     — TONY1 self-describing splittable record format: schema
               header + sync-marked blocks (the Avro container analog,
               reference :242 block sync, :446 schema channel)
  avro       — direct Avro object-container ingestion (existing datasets
               read in place, no conversion): spec binary codec, null +
               deflate + snappy codecs (pure-Python snappy in snappy.py),
               sync-scan split tiling (reference :242)
  reader     — FileSplitReader: C++ prefetch/shuffle engine via ctypes
               (native/datafeed.cc) with a pure-Python fallback; byte,
               ndarray, and local-spill delivery modes
  jax_feed   — decode to ndarray + assemble global sharded jax.Arrays via
               jax.make_array_from_process_local_data
  prefetch   — DevicePrefetcher: background decode + assembly + H2D into a
               bounded queue so input work overlaps device compute
               (consumed by models/loop.run_training)
"""

from tony_tpu.io.split import (FileSegment, compute_read_info,
                               full_records_in_split, split_length,
                               split_start)
from tony_tpu.io.framed import (FramedFormatError, FramedWriter,
                                is_framed_file, iter_file_records,
                                read_path_header)
from tony_tpu.io.avro import (AvroFormatError, AvroWriter, is_avro_file,
                              read_datum, write_datum)
from tony_tpu.io.reader import DataFeedError, FileSplitReader

# jax_feed / prefetch re-exports are lazy: they import numpy (and jax
# inside their functions), which orchestration-only installs — submit
# hosts, `tony convert` — do not carry (pyproject's "compute" extra).
_LAZY = {name: "tony_tpu.io.jax_feed"
         for name in ("array_batches", "global_batches", "record_size_for",
                      "records_to_array", "to_global_array")}
_LAZY.update({name: "tony_tpu.io.prefetch"
              for name in ("DevicePrefetcher", "PrefetchShapeError",
                           "elastic_epochs", "reader_epochs",
                           "synchronous_batches")})

__all__ = [
    "FileSegment", "compute_read_info", "full_records_in_split",
    "split_start", "split_length",
    "FramedWriter", "FramedFormatError", "is_framed_file",
    "iter_file_records", "read_path_header",
    "AvroWriter", "AvroFormatError", "is_avro_file",
    "read_datum", "write_datum",
    "FileSplitReader", "DataFeedError",
    *_LAZY,
]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module 'tony_tpu.io' has no attribute {name!r}")
