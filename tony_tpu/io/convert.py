"""Convert line- or fixed-record data files into splittable record files.

The on-ramp to the sharded data feed: training corpora usually arrive as
JSONL / text / fixed-size binary records, and re-framing them buys
block-level split sync, a schema channel, and variable-length records
across multi-host splits. Two output containers:

- ``--to framed`` (default): TONY1 (tony_tpu/io/framed.py).
- ``--to avro``: a spec-conformant Avro object container
  (tony_tpu/io/avro.py — the DataFileWriter analog of the reference's
  pipeline, HdfsAvroFileSplitReader.java) holding each record as one
  ``"bytes"`` datum, with ``--codec null|deflate|snappy`` — readable by
  any Avro implementation, payload-identical to the input records.

    python -m tony_tpu.io.convert corpus-*.jsonl --out-dir framed/
    tony convert corpus.txt --format lines --schema '{"field": "text"}'
    tony convert corpus.jsonl --to avro --codec snappy

One output file per input (``<name>.tony1`` / ``<name>.avro`` beside it
or under ``--out-dir``), so the converted corpus shards exactly like the
original file list.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterator

from tony_tpu.io.avro import AvroWriter
from tony_tpu.io.framed import DEFAULT_BLOCK_BYTES, FramedWriter


def iter_records(path: str, fmt: str, record_size: int) -> Iterator[bytes]:
    """Yield raw record payloads from an input file.

    jsonl/lines: one record per newline-terminated line (the newline is
    NOT part of the record — framing replaces it as the delimiter).
    fixed: consecutive ``record_size``-byte records; a short tail raises
    (silent truncation would drop data the caller believes was converted).
    """
    if fmt in ("jsonl", "lines"):
        with open(path, "rb") as f:
            for line in f:
                line = line.rstrip(b"\n")
                if not line and fmt == "jsonl":
                    continue          # blank lines are not JSON records
                if fmt == "jsonl":
                    json.loads(line)  # validate now, not mid-training
                yield line
    elif fmt == "fixed":
        if record_size <= 0:
            raise ValueError("--record-size is required for --format fixed")
        with open(path, "rb") as f:
            while True:
                rec = f.read(record_size)
                if not rec:
                    break
                if len(rec) < record_size:
                    raise ValueError(
                        f"{path}: trailing {len(rec)} bytes do not form a "
                        f"{record_size}-byte record")
                yield rec
    else:
        raise ValueError(f"unknown format {fmt!r}")


def convert_file(src: str, dest: str, fmt: str, schema: dict | str,
                 record_size: int = 0,
                 block_bytes: int = DEFAULT_BLOCK_BYTES,
                 to: str = "framed", codec: str = "null") -> int:
    """Convert one file; returns the number of records written. Writes to
    ``dest + '.tmp'`` and renames, so an interrupted run never leaves a
    half-framed file that readers would reject."""
    tmp = dest + ".tmp"
    try:
        # avro: each input record rides as one "bytes" datum —
        # payload-preserving and readable by any Avro implementation
        writer = (AvroWriter(tmp, "\"bytes\"", codec=codec)
                  if to == "avro"
                  else FramedWriter(tmp, schema=schema,
                                    block_bytes=block_bytes))
        with writer as w:
            for rec in iter_records(src, fmt, record_size):
                w.append(rec)
            count = w.records_written
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return count


def default_schema(fmt: str, record_size: int) -> dict:
    if fmt == "jsonl":
        return {"format": "jsonl"}
    if fmt == "lines":
        return {"format": "text-lines"}
    return {"format": "fixed", "record_size": record_size}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tony-convert",
        description="Convert data files to a splittable record container "
                    "(TONY1 framed, or an Avro object container)")
    parser.add_argument("inputs", nargs="+", help="input data files")
    parser.add_argument("--format", default="jsonl",
                        choices=("jsonl", "lines", "fixed"),
                        help="input record framing (default jsonl)")
    parser.add_argument("--record-size", type=int, default=0,
                        help="record byte size for --format fixed")
    parser.add_argument("--schema", default="",
                        help="JSON schema string stored in the file header "
                             "(default: derived from --format)")
    parser.add_argument("--out-dir", default="",
                        help="write <name>.tony1 / <name>.avro here "
                             "(default: beside each input)")
    parser.add_argument("--block-bytes", type=int,
                        default=DEFAULT_BLOCK_BYTES,
                        help="target framed block size")
    parser.add_argument("--to", default="framed",
                        choices=("framed", "avro"),
                        help="output container (default TONY1 framed; avro "
                             "stores records as 'bytes' datums)")
    parser.add_argument("--codec", default="null",
                        choices=("null", "deflate", "snappy"),
                        help="avro block codec (--to avro only)")
    args = parser.parse_args(argv)
    if args.codec != "null" and args.to != "avro":
        parser.error("--codec applies only to --to avro")
    if args.to == "avro" and args.schema:
        # the avro container's schema is always '"bytes"' (payload
        # preservation); silently dropping a user schema would lie
        parser.error("--schema applies only to --to framed (avro output "
                     "stores records as 'bytes' datums)")
    if args.to == "avro" and args.block_bytes != DEFAULT_BLOCK_BYTES:
        parser.error("--block-bytes applies only to --to framed (the avro "
                     "writer blocks by record count)")

    schema = (json.loads(args.schema) if args.schema
              else default_schema(args.format, args.record_size))
    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
    ext = ".avro" if args.to == "avro" else ".tony1"
    dests = []
    for src in args.inputs:
        base = os.path.basename(src)
        stem = base.rsplit(".", 1)[0] if "." in base else base
        out_dir = args.out_dir or os.path.dirname(os.path.abspath(src))
        dests.append(os.path.join(out_dir, stem + ext))
    # Same-stem inputs (a/corpus.jsonl + b/corpus.jsonl with --out-dir, or
    # a.jsonl + a.txt) would silently overwrite each other's output.
    seen: dict[str, str] = {}
    for src, dest in zip(args.inputs, dests):
        if dest in seen:
            parser.error(f"{src} and {seen[dest]} both convert to {dest}; "
                         f"rename an input or convert them separately")
        seen[dest] = src
    total = 0
    for src, dest in zip(args.inputs, dests):
        n = convert_file(src, dest, args.format, schema,
                         record_size=args.record_size,
                         block_bytes=args.block_bytes,
                         to=args.to, codec=args.codec)
        total += n
        print(f"{src} -> {dest}: {n} records")
    print(f"converted {total} records from {len(args.inputs)} file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
