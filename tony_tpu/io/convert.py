"""Convert line- or fixed-record data files into TONY1 framed files.

The on-ramp to the framed data feed (tony_tpu/io/framed.py — the
DataFileWriter analog of the reference's Avro pipeline,
HdfsAvroFileSplitReader.java): training corpora usually arrive as JSONL /
text / fixed-size binary records, and framing them buys block-level split
sync, a schema channel, and variable-length records across multi-host
splits.

    python -m tony_tpu.io.convert corpus-*.jsonl --out-dir framed/
    tony convert corpus.txt --format lines --schema '{"field": "text"}'

One output file per input (``<name>.tony1`` beside it or under
``--out-dir``), so the converted corpus shards exactly like the original
file list.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterator

from tony_tpu.io.framed import DEFAULT_BLOCK_BYTES, FramedWriter


def iter_records(path: str, fmt: str, record_size: int) -> Iterator[bytes]:
    """Yield raw record payloads from an input file.

    jsonl/lines: one record per newline-terminated line (the newline is
    NOT part of the record — framing replaces it as the delimiter).
    fixed: consecutive ``record_size``-byte records; a short tail raises
    (silent truncation would drop data the caller believes was converted).
    """
    if fmt in ("jsonl", "lines"):
        with open(path, "rb") as f:
            for line in f:
                line = line.rstrip(b"\n")
                if not line and fmt == "jsonl":
                    continue          # blank lines are not JSON records
                if fmt == "jsonl":
                    json.loads(line)  # validate now, not mid-training
                yield line
    elif fmt == "fixed":
        if record_size <= 0:
            raise ValueError("--record-size is required for --format fixed")
        with open(path, "rb") as f:
            while True:
                rec = f.read(record_size)
                if not rec:
                    break
                if len(rec) < record_size:
                    raise ValueError(
                        f"{path}: trailing {len(rec)} bytes do not form a "
                        f"{record_size}-byte record")
                yield rec
    else:
        raise ValueError(f"unknown format {fmt!r}")


def convert_file(src: str, dest: str, fmt: str, schema: dict | str,
                 record_size: int = 0,
                 block_bytes: int = DEFAULT_BLOCK_BYTES) -> int:
    """Convert one file; returns the number of records written. Writes to
    ``dest + '.tmp'`` and renames, so an interrupted run never leaves a
    half-framed file that readers would reject."""
    tmp = dest + ".tmp"
    try:
        with FramedWriter(tmp, schema=schema, block_bytes=block_bytes) as w:
            for rec in iter_records(src, fmt, record_size):
                w.append(rec)
            count = w.records_written
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return count


def default_schema(fmt: str, record_size: int) -> dict:
    if fmt == "jsonl":
        return {"format": "jsonl"}
    if fmt == "lines":
        return {"format": "text-lines"}
    return {"format": "fixed", "record_size": record_size}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tony-convert",
        description="Convert data files to the TONY1 framed record format")
    parser.add_argument("inputs", nargs="+", help="input data files")
    parser.add_argument("--format", default="jsonl",
                        choices=("jsonl", "lines", "fixed"),
                        help="input record framing (default jsonl)")
    parser.add_argument("--record-size", type=int, default=0,
                        help="record byte size for --format fixed")
    parser.add_argument("--schema", default="",
                        help="JSON schema string stored in the file header "
                             "(default: derived from --format)")
    parser.add_argument("--out-dir", default="",
                        help="write <name>.tony1 here (default: beside "
                             "each input)")
    parser.add_argument("--block-bytes", type=int,
                        default=DEFAULT_BLOCK_BYTES,
                        help="target framed block size")
    args = parser.parse_args(argv)

    schema = (json.loads(args.schema) if args.schema
              else default_schema(args.format, args.record_size))
    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
    dests = []
    for src in args.inputs:
        base = os.path.basename(src)
        stem = base.rsplit(".", 1)[0] if "." in base else base
        out_dir = args.out_dir or os.path.dirname(os.path.abspath(src))
        dests.append(os.path.join(out_dir, stem + ".tony1"))
    # Same-stem inputs (a/corpus.jsonl + b/corpus.jsonl with --out-dir, or
    # a.jsonl + a.txt) would silently overwrite each other's output.
    seen: dict[str, str] = {}
    for src, dest in zip(args.inputs, dests):
        if dest in seen:
            parser.error(f"{src} and {seen[dest]} both convert to {dest}; "
                         f"rename an input or convert them separately")
        seen[dest] = src
    total = 0
    for src, dest in zip(args.inputs, dests):
        n = convert_file(src, dest, args.format, schema,
                         record_size=args.record_size,
                         block_bytes=args.block_bytes)
        total += n
        print(f"{src} -> {dest}: {n} records")
    print(f"converted {total} records from {len(args.inputs)} file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
