"""Sharded file-split reader: the framework's data-feed engine.

Rebuild of the reference's ``HdfsAvroFileSplitReader`` (reference: tony-core/
src/main/java/com/linkedin/tony/io/HdfsAvroFileSplitReader.java) as a
TPU-native component: the executor is Python, so no py4j gateway is needed —
the engine is a C++ shared library (``native/datafeed.cc``) reached over
ctypes, with a pure-Python fallback carrying identical semantics when no
toolchain is available.

Semantics kept from the reference:
  * contiguous global byte-range split across tasks
    (``split.compute_read_info``, reference :286-297)
  * record-boundary sync at split starts (reference :242 Avro block sync;
    here fixed-size, newline, or TONY1 framed-block framing)
  * a schema channel: framed files carry a JSON schema in their header,
    surfaced via :meth:`FileSplitReader.schema_json` (the analog of
    ``getSchemaJson`` :446)
  * bounded prefetch buffer, optionally shuffling — a streaming shuffle
    whose window is the buffer capacity (reference InternalBuffer :678)
  * three delivery modes: packed byte batches (``next_batch``), ndarray
    batches (jax_feed), and local-disk spill for batches larger than
    memory (``next_batch_spill``, the analog of
    ``nextBatchFileLocalSpill`` :525)

Usage::

    reader = FileSplitReader(paths, task_index=i, task_num=n,
                             shuffle=True, seed=epoch)   # framing auto
    print(reader.schema_json)   # "" unless the files are TONY1 framed
    for rec in reader:          # bytes objects
        ...
    reader.close()
"""

from __future__ import annotations

import collections
import ctypes
import logging
import random
import weakref
from typing import Iterator

from tony_tpu.storage import is_remote, sopen
from tony_tpu.io import avro as _avro
from tony_tpu.io import framed as _framed
from tony_tpu.io.split import FileSegment, compute_read_info
from tony_tpu.io.native.build import load_native

log = logging.getLogger(__name__)

_BATCH_BUF_CAP = 1 << 22          # 4 MiB packed-record buffer per pull
_DEFAULT_CAPACITY = 1024


class DataFeedError(RuntimeError):
    pass


class _NativeImpl:
    """ctypes wrapper over the C++ engine (producer thread lives in C++)."""

    def __init__(self, segments: list[FileSegment], record_size: int,
                 capacity: int, shuffle: bool, seed: int, lib) -> None:
        self._lib = lib
        n = len(segments)
        paths = (ctypes.c_char_p * n)(
            *[s.path.encode() for s in segments])
        offsets = (ctypes.c_int64 * n)(*[s.offset for s in segments])
        lengths = (ctypes.c_int64 * n)(*[s.length for s in segments])
        self._h = lib.tdf_open(paths, offsets, lengths, n, record_size,
                               capacity, 1 if shuffle else 0, seed)
        if not self._h:
            raise DataFeedError("tdf_open failed")
        self._buf = ctypes.create_string_buffer(_BATCH_BUF_CAP)
        self._lens = (ctypes.c_int64 * 4096)()
        # Guarantees tdf_close even when the reader is dropped without
        # close() — otherwise the C++ producer thread blocks in Push()
        # forever, pinning the thread, fd, and buffered records.
        self._finalizer = weakref.finalize(self, _close_native, lib, self._h)

    def next_batch(self, max_records: int) -> list[bytes]:
        if self._h is None:
            # Match the Python impl's post-close behavior instead of handing
            # a NULL handle to C++ (nullptr deref, interpreter crash).
            return []
        max_records = min(max_records, len(self._lens))
        n = self._lib.tdf_next_batch(self._h, self._buf, _BATCH_BUF_CAP,
                                     self._lens, max_records)
        if n == -1:
            raise DataFeedError(self._lib.tdf_error(self._h).decode())
        if n == -2:
            raise DataFeedError(
                f"record larger than {_BATCH_BUF_CAP} byte pull buffer")
        # Copy only the bytes actually used (Array.raw would materialize the
        # whole 4 MiB buffer per pull).
        used = sum(self._lens[i] for i in range(n))
        raw = ctypes.string_at(self._buf, used)
        out, pos = [], 0
        for i in range(n):
            ln = self._lens[i]
            out.append(raw[pos:pos + ln])
            pos += ln
        return out

    def close(self) -> None:
        self._finalizer()
        self._h = None


def _close_native(lib, handle) -> None:
    lib.tdf_close(handle)


_SENTINEL = object()


def _prefetch_producer(records, q, stop, error_box) -> None:
    """Prefetch producer body (module-level: must not close over the
    reader). Decodes ahead of the training loop; a decode error lands in
    ``error_box`` and is re-raised by the consumer — never swallowed in a
    daemon thread. The trailing sentinel is best-effort with a bounded
    loop: consumers use timeout-gets that re-check ``stop``, so a missing
    sentinel cannot deadlock them."""
    import queue
    try:
        for rec in records:
            while not stop.is_set():
                try:
                    q.put(rec, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if stop.is_set():
                return
    except BaseException as e:
        error_box.append(e)
    finally:
        for _ in range(50):
            try:
                q.put(_SENTINEL, timeout=0.1)
                break
            except queue.Full:
                if stop.is_set():
                    break


def _stop_producer(stop, q) -> None:
    """Finalizer for dropped readers: release the producer thread (it
    exits its put loop once ``stop`` is set and the queue has room)."""
    stop.set()
    try:
        q.get_nowait()
    except Exception:
        pass


class _PythonImpl:
    """Pure-Python engine: same framing, sync, and windowed-shuffle
    semantics as the C++ engine, with an optional background PREFETCH
    thread (``prefetch=True``) that decodes ahead into a bounded queue —
    the DataFetcher-thread property (reference InternalBuffer:678) for
    the formats only this engine speaks (Avro). Without it the impl is
    fully synchronous (toolchain-less hosts, deterministic tests)."""

    def __init__(self, segments: list[FileSegment], record_size: int,
                 capacity: int, shuffle: bool, seed: int,
                 prefetch: bool = False) -> None:
        self._records = self._generate(segments, record_size)
        # list for shuffle (O(1) swap-remove at a random slot), deque for
        # FIFO (O(1) popleft; list.pop(0) would shift the whole window).
        self._pool: list[bytes] | collections.deque[bytes] = (
            [] if shuffle else collections.deque())
        self._capacity = max(1, capacity)
        self._shuffle = shuffle
        self._rng = random.Random(seed)
        self._exhausted = False
        self._queue = None
        self._producer = None
        #: one-slot box the producer stores a decode error into (read and
        #: re-raised by the consumer in _fill)
        self._error_box: list = []
        if prefetch:
            import queue
            import threading
            import weakref
            # queue depth is capacity/4, ON TOP of the capacity-sized
            # shuffle pool: enough decode-ahead overlap without silently
            # doubling the documented buffer residency
            self._queue = queue.Queue(maxsize=max(8, self._capacity // 4))
            self._stop = threading.Event()
            # The producer must NOT hold a reference to self (it would pin
            # the reader and the finalizer below could never fire): it
            # gets the generator/queue/flag directly.
            self._producer = threading.Thread(
                target=_prefetch_producer,
                args=(self._records, self._queue, self._stop,
                      self._error_box),
                name="tony-datafeed-prefetch", daemon=True)
            self._producer.start()
            # A reader dropped without close() must not leave the producer
            # spinning on a full queue forever (the native impl guards the
            # same hazard with its own finalizer).
            self._finalizer = weakref.finalize(
                self, _stop_producer, self._stop, self._queue)

    @staticmethod
    def _generate(segments: list[FileSegment],
                  record_size: int) -> Iterator[bytes]:
        for seg in segments:
            if record_size == -1:           # TONY1 framed blocks
                yield from _framed.iter_segment_records(
                    seg.path, seg.offset, seg.length)
                continue
            if record_size == -2:           # Avro object container
                yield from _avro.iter_segment_records(
                    seg.path, seg.offset, seg.length)
                continue
            with sopen(seg.path) as f:
                if record_size > 0:
                    first = -(-seg.offset // record_size)
                    end_excl = -(-(seg.offset + seg.length) // record_size)
                    f.seek(first * record_size)
                    for _ in range(first, end_excl):
                        data = f.read(record_size)
                        if not data:
                            break
                        yield data
                else:
                    # Hadoop line-split convention: a reader starting
                    # mid-file always discards through the first '\n' (even
                    # when the offset lands exactly on a line start), and
                    # reads lines while position-before-line <= end — so the
                    # line straddling/starting at a boundary belongs to
                    # exactly one split.
                    f.seek(seg.offset)
                    pos = seg.offset
                    if seg.offset > 0:
                        skipped = f.readline()
                        pos += len(skipped)
                    end = seg.offset + seg.length
                    while pos <= end:
                        line = f.readline()
                        if not line:
                            break
                        pos += len(line)
                        yield line.rstrip(b"\n")

    def _fill(self) -> None:
        if self._queue is not None:
            import queue
            while not self._exhausted and len(self._pool) < self._capacity:
                try:
                    # timeout + stop re-check: a cross-thread close() may
                    # retire the producer before its sentinel lands
                    item = self._queue.get(timeout=0.2)
                except queue.Empty:
                    if self._stop.is_set():
                        self._exhausted = True
                    continue
                if item is _SENTINEL:
                    self._exhausted = True
                    if self._error_box:
                        raise self._error_box.pop()
                else:
                    self._pool.append(item)
            return
        while not self._exhausted and len(self._pool) < self._capacity:
            try:
                self._pool.append(next(self._records))
            except StopIteration:
                self._exhausted = True

    def next_batch(self, max_records: int) -> list[bytes]:
        out: list[bytes] = []
        while len(out) < max_records:
            self._fill()
            if not self._pool:
                break
            if self._shuffle:
                idx = self._rng.randrange(len(self._pool))
                self._pool[idx], self._pool[-1] = (self._pool[-1],
                                                   self._pool[idx])
                out.append(self._pool.pop())        # swap-remove: O(1)
            else:
                out.append(self._pool.popleft())    # FIFO: O(1)
        return out

    def close(self) -> None:
        self._pool.clear()
        self._exhausted = True
        if self._producer is not None:
            # stop the producer FIRST: gen.close() on a generator another
            # thread is executing raises ValueError
            self._stop.set()
            while True:       # unblock a put() stuck on a full queue
                try:
                    self._queue.get_nowait()
                except Exception:
                    break
            self._producer.join(timeout=5)
            if self._producer.is_alive():
                # stuck inside the generator (hung IO): leave the daemon
                # thread to die with the process rather than raise from
                # closing a generator another thread is executing — but
                # drain and DROP the queue (and the finalizer's reference
                # to it) so already-decoded records are GC-able instead of
                # pinned behind a wedged thread. The producer keeps its own
                # queue reference; any residual puts it lands before dying
                # are bounded by the queue capacity.
                log.warning("datafeed prefetch thread did not exit; "
                            "leaving generator to the daemon thread")
                while True:
                    try:
                        self._queue.get_nowait()
                    except Exception:
                        break
                self._finalizer.detach()   # stop already set; queue drained
                self._queue = None
                return
        # Release the fd held by the suspended generator now, not at GC time
        # (the native impl guarantees this via its finalizer).
        self._records.close()


class FileSplitReader:
    """Task-sharded record reader over a list of files.

    Parameters mirror the reference's constructor (HdfsAvroFileSplitReader
    :347 — conf, paths, taskIndex, numTasks, shuffle), with ``record_size``
    selecting the framing (0 = newline-delimited, >0 = fixed-size records).
    """

    def __init__(self, paths: list[str], task_index: int = 0,
                 task_num: int = 1, record_size: int | None = None,
                 shuffle: bool = False, seed: int = 0,
                 capacity: int = _DEFAULT_CAPACITY,
                 use_native: bool | None = None,
                 sizes: list[int] | None = None) -> None:
        #: schema channel (reference getSchemaJson:446): the JSON schema
        #: from the first framed/Avro file's header, "" for unframed data.
        self.schema_json = ""
        # record_size None = auto: every path is classified (TONY1 framed /
        # Avro container / unframed) and the kinds must AGREE — parsing a
        # framed or Avro file as lines would silently yield garbage, so a
        # mixed list is rejected whatever the ordering. -1 forces framed,
        # -2 forces Avro (header read below raises on a mismatched file).
        if record_size is None:
            if paths:
                def _kind(p: str) -> int:
                    if _framed.is_framed_file(p):
                        return -1
                    return -2 if _avro.is_avro_file(p) else 0
                kinds = [_kind(p) for p in paths]
                if len(set(kinds)) > 1:
                    names = {-1: "TONY1 framed", -2: "Avro", 0: "unframed"}
                    detail = ", ".join(
                        f"{p} is {names[k]}" for p, k in zip(paths, kinds))
                    raise ValueError(f"mixed framings ({detail}); pass "
                                     f"record_size explicitly")
                record_size = kinds[0]
            else:
                record_size = 0
        if record_size < -2:
            raise ValueError("record_size must be -2 (avro), -1 (framed), "
                             "0 (lines), or a positive fixed size")
        self.record_size = record_size
        if paths and record_size == -1:
            self.schema_json = _framed.read_path_header(paths[0]).schema_json
        elif paths and record_size == -2:
            self.schema_json = _avro.read_path_header(paths[0]).schema_json
        self.segments = compute_read_info(paths, task_index, task_num,
                                          sizes=sizes)
        #: records pulled past a spill-call budget, served before new pulls
        self._spill_carry: list[bytes] = []
        # Avro record boundaries are schema-driven (skip_datum walks the
        # schema), so the Avro arm runs on the Python engine; the C++
        # engine speaks the byte-framed formats (fixed/lines/TONY1).
        if record_size == -2:
            if use_native is True:
                raise DataFeedError(
                    "the native engine does not decode Avro (record "
                    "boundaries are schema-driven); omit use_native")
            use_native = False
        # Remote (gs://) inputs stream through the storage seam's ranged
        # reader — the C++ engine only speaks local fds.
        if any(is_remote(p) for p in paths):
            if use_native is True:
                raise DataFeedError(
                    "the native engine reads local files only; remote "
                    "(gs://) inputs use the Python engine — omit use_native")
            use_native = False
        lib = load_native() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise DataFeedError("native data-feed requested but unavailable")
        if lib is not None:
            self._impl: _NativeImpl | _PythonImpl = _NativeImpl(
                self.segments, record_size, capacity, shuffle, seed, lib)
            self.is_native = True
        else:
            # Avro and remote (gs://) inputs are production-served by the
            # Python engine, so they get the background prefetch thread
            # (the C++ engine's DataFetcher property) — for remote inputs
            # it overlaps ranged fetches with training; the plain local
            # fallback stays synchronous. Window contents are identical
            # either way (single FIFO producer), so shuffle determinism
            # is unchanged.
            self._impl = _PythonImpl(
                self.segments, record_size, capacity, shuffle, seed,
                prefetch=(record_size == -2
                          or any(is_remote(p) for p in paths)))
            self.is_native = False

    def schema(self) -> dict:
        """Parsed schema from the framed-file header ({} when absent)."""
        import json
        return json.loads(self.schema_json) if self.schema_json else {}

    def next_batch(self, max_records: int = 256) -> list[bytes]:
        """Up to ``max_records`` records; [] at end of split (the analog of
        the reference's nextBatchBytes :598)."""
        if self._spill_carry:
            # records pulled past a spill-call budget are served first so
            # mixing delivery modes never skips data
            out = self._spill_carry[:max_records]
            self._spill_carry = self._spill_carry[max_records:]
            return out
        return self._impl.next_batch(max_records)

    def next_batch_spill(self, spill_dir: str, max_records: int = 1 << 62,
                         max_bytes: int = 1 << 62) -> str | None:
        """Local-spill delivery (reference nextBatchFileLocalSpill:525):
        stream up to ``max_records``/``max_bytes`` of records into a TONY1
        framed file under ``spill_dir`` and return its path — for batches
        too large to hold in memory. Returns None at end of split. Read
        back with :func:`tony_tpu.io.framed.iter_file_records`; the
        caller owns deletion."""
        import os
        import uuid
        os.makedirs(spill_dir, exist_ok=True)
        path = os.path.join(spill_dir, f"spill-{uuid.uuid4().hex}.tony1")
        wrote = 0
        # Records pulled past a previous call's budget carry over — a pull
        # batch must never be dropped on the floor at a budget boundary.
        carry = self._spill_carry
        with _framed.FramedWriter(path, schema=self.schema_json or {}) as w:
            # budget applies only once a record is in: a header larger than
            # max_bytes must not masquerade as end-of-split (None)
            while wrote < max_records and (wrote == 0
                                           or w.total_bytes < max_bytes):
                batch = carry or self._impl.next_batch(
                    min(256, max_records - wrote))
                carry = []
                if not batch:
                    break
                for i, rec in enumerate(batch):
                    w.append(rec)
                    wrote += 1
                    if wrote >= max_records or w.total_bytes >= max_bytes:
                        carry = batch[i + 1:]
                        break
        self._spill_carry = carry
        if wrote == 0:
            os.remove(path)
            return None
        return path

    def __iter__(self) -> Iterator[bytes]:
        while True:
            batch = self.next_batch()
            if not batch:
                return
            yield from batch

    def close(self) -> None:
        self._impl.close()

    def __enter__(self) -> "FileSplitReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
