// Native data-feed engine: background-prefetched, bounded-buffer, optionally
// shuffling record reader over per-file byte-range segments.
//
// TPU-native analog of the reference's JVM data-feed engine (reference:
// tony-core/src/main/java/com/linkedin/tony/io/HdfsAvroFileSplitReader.java:
// DataFetcher thread :176, InternalBuffer bounded/shuffle buffer :678, record
// boundary sync :242). The reference runs this engine in the TaskExecutor JVM
// and ships batches to Python over py4j; here the engine is a C++ shared
// library the Python executor loads over ctypes — same producer/consumer
// design, no socket hop.
//
// Record framings:
//   record_size > 0  — fixed-size records (packed tensors); a record belongs
//                      to the segment where its start byte falls.
//   record_size == 0 — newline-delimited records (jsonl/text); a reader whose
//                      offset is mid-record syncs forward past the next '\n'
//                      (the straddling record belongs to the previous split,
//                      which reads past its end to finish it).
//   record_size == -1 — TONY1 framed blocks (self-describing, variable-
//                      length; see tony_tpu/io/framed.py): the file header
//                      carries a 16-byte sync marker and a JSON schema; a
//                      block belongs to the split where its sync STARTS
//                      (the Avro block-sync convention, reference :242).
//
// Concurrency: one producer thread fills a bounded pool; consumers pop under
// a mutex. In shuffle mode the pop picks a uniformly random pool slot
// (swap-remove), giving a streaming shuffle with window = capacity, matching
// the reference's InternalBuffer shuffle semantics.
//
// Build: g++ -O2 -shared -fPIC -pthread datafeed.cc -o _datafeed.so
// (driven by tony_tpu/io/native/build.py).

#include <condition_variable>
#include <deque>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Segment {
  std::string path;
  int64_t offset;
  int64_t length;
};

struct Record {
  std::vector<char> data;
};

class Reader {
 public:
  Reader(std::vector<Segment> segments, int64_t record_size, int capacity,
         bool shuffle, uint64_t seed)
      : segments_(std::move(segments)),
        record_size_(record_size),
        capacity_(capacity < 1 ? 1 : capacity),
        shuffle_(shuffle),
        rng_(seed) {
    producer_ = std::thread([this] { Produce(); });
  }

  ~Reader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_not_full_.notify_all();
    cv_not_empty_.notify_all();
    if (producer_.joinable()) producer_.join();
  }

  // Pops up to max_records records, packing bytes back-to-back into out and
  // per-record lengths into rec_lens. Returns the record count, 0 on EOF,
  // -1 on producer error, -2 if out_cap can't hold even one record.
  int64_t NextBatch(char* out, int64_t out_cap, int64_t* rec_lens,
                    int64_t max_records) {
    int64_t n = 0;
    int64_t used = 0;
    std::unique_lock<std::mutex> lk(mu_);
    while (n < max_records) {
      cv_not_empty_.wait(lk, [this] {
        return !pool_.empty() || done_ || closed_ || !error_.empty();
      });
      if (!error_.empty()) return -1;
      if (pool_.empty()) break;  // done_ or closed_: drain finished
      size_t slot = 0;
      if (shuffle_ && pool_.size() > 1) {
        slot = std::uniform_int_distribution<size_t>(0, pool_.size() - 1)(rng_);
      }
      int64_t len = static_cast<int64_t>(pool_[slot].data.size());
      if (used + len > out_cap) {
        if (n == 0) return -2;
        break;  // batch full; leave record for the next call
      }
      std::memcpy(out + used, pool_[slot].data.data(), len);
      rec_lens[n++] = len;
      used += len;
      if (shuffle_) {
        pool_[slot] = std::move(pool_.back());
        pool_.pop_back();  // swap-remove: O(1), order irrelevant
      } else {
        pool_.pop_front();  // FIFO: preserve record order (slot == 0)
      }
      cv_not_full_.notify_one();
      // Return a partial batch rather than blocking for stragglers once the
      // pool is drained mid-batch and the producer is still running: only
      // block for the FIRST record.
      if (pool_.empty() && !done_) break;
    }
    return n;
  }

  const char* Error() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_.c_str();
  }

 private:
  void Fail(const std::string& msg) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      error_ = msg;
    }
    cv_not_empty_.notify_all();
  }

  // Pushes a record into the bounded pool; blocks while full.
  // Returns false when the reader is being closed.
  bool Push(Record&& rec) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_not_full_.wait(lk, [this] {
      return static_cast<int>(pool_.size()) < capacity_ || closed_;
    });
    if (closed_) return false;
    pool_.push_back(std::move(rec));
    cv_not_empty_.notify_one();
    return true;
  }

  void Produce() {
    for (const Segment& seg : segments_) {
      if (!ProduceSegment(seg)) break;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      done_ = true;
    }
    cv_not_empty_.notify_all();
  }

  bool ProduceSegment(const Segment& seg) {
    FILE* f = std::fopen(seg.path.c_str(), "rb");
    if (!f) {
      Fail("cannot open " + seg.path);
      return false;
    }
    bool ok = record_size_ > 0   ? ProduceFixed(seg, f)
              : record_size_ == 0 ? ProduceLines(seg, f)
                                  : ProduceFramed(seg, f);
    std::fclose(f);
    return ok;
  }

  // --- TONY1 framed blocks (framed.py is the format's reference impl) ----
  static constexpr int64_t kSyncLen = 16;
  static constexpr uint32_t kMaxBlockRecords = 1u << 24;
  static constexpr uint32_t kMaxBlockBytes = 1u << 30;

  static uint32_t ReadU32(const unsigned char* p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
  }

  // First sync position >= start and < limit, or -1.
  static int64_t FindSync(FILE* f, const std::string& sync, int64_t start,
                          int64_t limit) {
    if (std::fseek(f, start, SEEK_SET) != 0) return -1;
    std::string buf;
    int64_t base = start;  // file position of buf[0]
    char chunk[1 << 16];
    while (base < limit) {
      size_t got = std::fread(chunk, 1, sizeof(chunk), f);
      if (got == 0) return -1;
      buf.append(chunk, got);
      size_t idx = buf.find(sync);
      if (idx != std::string::npos) {
        int64_t found = base + static_cast<int64_t>(idx);
        return found < limit ? found : -1;
      }
      size_t keep = sync.size() - 1;
      if (buf.size() > keep) {
        base += static_cast<int64_t>(buf.size() - keep);
        buf.erase(0, buf.size() - keep);
      }
    }
    return -1;
  }

  bool ProduceFramed(const Segment& seg, FILE* f) {
    // header: magic(6) + sync(16) + schema_len(4) + schema
    unsigned char head[6 + kSyncLen + 4];
    if (std::fread(head, 1, sizeof(head), f) != sizeof(head) ||
        std::memcmp(head, "TONY1\0", 6) != 0) {
      Fail("not a TONY1 framed file: " + seg.path);
      return false;
    }
    std::string sync(reinterpret_cast<char*>(head + 6), kSyncLen);
    uint32_t schema_len = ReadU32(head + 6 + kSyncLen);
    int64_t data_start = static_cast<int64_t>(sizeof(head)) + schema_len;
    // A corrupt schema_len must fail loudly (framed.py raises 'truncated
    // schema header'), not silently report an empty split.
    if (std::fseek(f, 0, SEEK_END) != 0) {
      Fail("seek failed in " + seg.path);
      return false;
    }
    int64_t file_size = std::ftell(f);
    if (data_start > file_size) {
      Fail("truncated schema header in " + seg.path);
      return false;
    }
    int64_t end = seg.offset + seg.length;
    int64_t pos = seg.offset > data_start ? seg.offset : data_start;
    if (pos >= end) return true;
    pos = FindSync(f, sync, pos, end);
    std::vector<char> payload;
    while (pos != -1 && pos < end) {
      if (std::fseek(f, pos, SEEK_SET) != 0) {
        Fail("seek failed in " + seg.path);
        return false;
      }
      unsigned char bh[kSyncLen + 8];
      size_t got = std::fread(bh, 1, sizeof(bh), f);
      if (got == 0) break;  // clean EOF after the previous block
      if (got != sizeof(bh) ||
          std::memcmp(bh, sync.data(), kSyncLen) != 0) {
        Fail("corrupt block header in " + seg.path);
        return false;
      }
      uint32_t count = ReadU32(bh + kSyncLen);
      uint32_t size = ReadU32(bh + kSyncLen + 4);
      if (count > kMaxBlockRecords || size > kMaxBlockBytes) {
        Fail("implausible block in " + seg.path);
        return false;
      }
      payload.resize(size);
      if (size > 0 && std::fread(payload.data(), 1, size, f) != size) {
        Fail("truncated block in " + seg.path);
        return false;
      }
      size_t p = 0;
      for (uint32_t i = 0; i < count; ++i) {
        if (p + 4 > size) {
          Fail("corrupt block payload in " + seg.path);
          return false;
        }
        uint32_t rlen =
            ReadU32(reinterpret_cast<unsigned char*>(payload.data()) + p);
        p += 4;
        if (p + rlen > size) {
          Fail("corrupt record length in " + seg.path);
          return false;
        }
        Record rec;
        rec.data.assign(payload.data() + p, payload.data() + p + rlen);
        p += rlen;
        if (!Push(std::move(rec))) return false;
      }
      pos += static_cast<int64_t>(sizeof(bh)) + size;  // blocks back-to-back
    }
    return true;
  }

  bool ProduceFixed(const Segment& seg, FILE* f) {
    // First record whose start byte is >= seg.offset; read records whose
    // start byte is < seg.offset + seg.length (may read past the end).
    int64_t first = (seg.offset + record_size_ - 1) / record_size_;
    int64_t end_excl = (seg.offset + seg.length + record_size_ - 1) / record_size_;
    if (std::fseek(f, first * record_size_, SEEK_SET) != 0) {
      Fail("seek failed in " + seg.path);
      return false;
    }
    for (int64_t i = first; i < end_excl; ++i) {
      Record rec;
      rec.data.resize(record_size_);
      size_t got = std::fread(rec.data.data(), 1, record_size_, f);
      if (got == 0) break;  // trailing partial file
      if (static_cast<int64_t>(got) < record_size_) {
        rec.data.resize(got);  // trailing short record: deliver as-is
      }
      if (!Push(std::move(rec))) return false;
    }
    return true;
  }

  bool ProduceLines(const Segment& seg, FILE* f) {
    if (std::fseek(f, seg.offset, SEEK_SET) != 0) {
      Fail("seek failed in " + seg.path);
      return false;
    }
    int64_t pos = seg.offset;
    // Hadoop line-split convention: a mid-file reader always discards
    // through the first '\n' (even when the offset lands exactly on a line
    // start — that line belongs to the previous split, which reads while
    // pos <= end). Offset 0 starts clean.
    if (seg.offset > 0) {
      int c;
      while ((c = std::fgetc(f)) != EOF) {
        ++pos;
        if (c == '\n') break;
      }
    }
    int64_t end = seg.offset + seg.length;
    std::vector<char> line;
    while (pos <= end) {  // line starting AT end is ours (next split skips it)
      line.clear();
      int c;
      while ((c = std::fgetc(f)) != EOF) {
        ++pos;
        if (c == '\n') break;
        line.push_back(static_cast<char>(c));
      }
      if (line.empty() && c == EOF) break;
      Record rec;
      rec.data = line;
      if (!Push(std::move(rec))) return false;
      if (c == EOF) break;
    }
    return true;
  }

  std::vector<Segment> segments_;
  const int64_t record_size_;
  const int capacity_;
  const bool shuffle_;
  std::mt19937_64 rng_;

  std::mutex mu_;
  std::condition_variable cv_not_empty_, cv_not_full_;
  std::deque<Record> pool_;
  bool done_ = false;
  bool closed_ = false;
  std::string error_;
  std::thread producer_;
};

}  // namespace

extern "C" {

void* tdf_open(const char** paths, const int64_t* offsets,
               const int64_t* lengths, int32_t nsegments, int64_t record_size,
               int32_t capacity, int32_t shuffle, uint64_t seed) {
  std::vector<Segment> segs;
  segs.reserve(nsegments);
  for (int32_t i = 0; i < nsegments; ++i) {
    segs.push_back(Segment{paths[i], offsets[i], lengths[i]});
  }
  return new Reader(std::move(segs), record_size, capacity, shuffle != 0, seed);
}

int64_t tdf_next_batch(void* h, char* out, int64_t out_cap, int64_t* rec_lens,
                       int64_t max_records) {
  return static_cast<Reader*>(h)->NextBatch(out, out_cap, rec_lens,
                                            max_records);
}

const char* tdf_error(void* h) { return static_cast<Reader*>(h)->Error(); }

void tdf_close(void* h) { delete static_cast<Reader*>(h); }

}  // extern "C"
