"""Lazy on-demand build + ctypes load of the native data-feed library.

The reference ships its data-feed engine pre-built inside the fat jar; here
the C++ core is compiled once per host (g++ -O2 -shared -fPIC -pthread) into
a cache directory and memoized. Loading is best-effort: callers fall back to
the pure-Python reader when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "datafeed.cc")
_LIB_NAME = "_tony_datafeed.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _cache_dir() -> str:
    base = os.environ.get("TONY_NATIVE_CACHE") or os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "tony_tpu")
    os.makedirs(base, exist_ok=True)
    return base


def _compile(lib_path: str) -> bool:
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", lib_path]
    tmp = None
    try:
        # Build into a temp name then rename: atomic against concurrent
        # executors on the same host racing to build the cache entry.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(lib_path))
        os.close(fd)
        cmd[-1] = tmp
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.info("native data-feed build unavailable (%s); using python path", e)
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.tdf_open.restype = ctypes.c_void_p
    lib.tdf_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64]
    lib.tdf_next_batch.restype = ctypes.c_int64
    lib.tdf_next_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    lib.tdf_error.restype = ctypes.c_char_p
    lib.tdf_error.argtypes = [ctypes.c_void_p]
    lib.tdf_close.restype = None
    lib.tdf_close.argtypes = [ctypes.c_void_p]
    return lib


def load_native() -> ctypes.CDLL | None:
    """The memoized native library, or None when it can't be built/loaded."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        lib_path = os.path.join(_cache_dir(), _LIB_NAME)
        try:
            if (not os.path.exists(lib_path)
                    or os.path.getmtime(lib_path) < os.path.getmtime(_SRC)):
                if not _compile(lib_path):
                    _load_failed = True
                    return None
            _lib = _bind(ctypes.CDLL(lib_path))
        except OSError as e:
            log.info("native data-feed load failed (%s); using python path", e)
            _load_failed = True
            return None
        return _lib
