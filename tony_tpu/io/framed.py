"""TONY1 framed records: self-describing, splittable, variable-length.

The reference's data feed is Avro-native: files carry their schema in a
header (served to Python over the ``getSchemaJson`` channel,
HdfsAvroFileSplitReader.java:446) and records live in blocks separated by
a per-file random 16-byte sync marker, which is what makes byte-range
splits safe — a reader landing mid-file scans forward to the next marker
(:242). TONY1 keeps exactly those load-bearing properties with a format
simple enough to write from any language:

```
file header:
    magic        6 bytes   b"TONY1\\0"
    sync         16 bytes  random per file
    schema_len   4 bytes   LE uint32
    schema       schema_len bytes of JSON (utf-8)
blocks, repeating until EOF:
    sync         16 bytes
    count        4 bytes   LE uint32  records in this block
    size         4 bytes   LE uint32  payload bytes
    payload      count x (4-byte LE uint32 length + record bytes)
```

Split semantics (identical to the Avro convention): a block belongs to
the split in which its sync marker STARTS; a reader whose offset lands
mid-block scans forward to the next marker and reads blocks whose start
position precedes its split end (possibly reading past the end).
"""

from __future__ import annotations

import json
import os
import secrets
import struct

from tony_tpu.storage import sopen, ssize
from typing import BinaryIO, Iterator

MAGIC = b"TONY1\0"
SYNC_LEN = 16
_HDR_FIXED = len(MAGIC) + SYNC_LEN + 4       # magic + sync + schema_len
_U32 = struct.Struct("<I")
#: sanity bounds applied when validating a candidate block header
MAX_BLOCK_RECORDS = 1 << 24
MAX_BLOCK_BYTES = 1 << 30
DEFAULT_BLOCK_BYTES = 256 * 1024


class FramedFormatError(ValueError):
    pass


class FileHeader:
    __slots__ = ("sync", "schema_json", "data_start")

    def __init__(self, sync: bytes, schema_json: str, data_start: int):
        self.sync = sync
        self.schema_json = schema_json
        self.data_start = data_start

    @property
    def schema(self) -> dict:
        return json.loads(self.schema_json) if self.schema_json else {}


def is_framed_file(path: str) -> bool:
    """True when ``path`` starts with the TONY1 magic. A missing/unreadable
    file raises OSError — swallowing it here would misreport a typo'd path
    as "not framed" and send callers down a framing-mismatch rabbit hole."""
    # magic probe via a ranged read: a scan-sized buffered stream would
    # fetch MBs of a remote object to look at 6 bytes
    from tony_tpu.storage import storage_for
    return storage_for(path).read_range(path, 0, len(MAGIC)) == MAGIC


def read_header(f: BinaryIO) -> FileHeader:
    f.seek(0)
    head = f.read(_HDR_FIXED)
    if len(head) < _HDR_FIXED or not head.startswith(MAGIC):
        raise FramedFormatError("not a TONY1 framed file")
    sync = head[len(MAGIC):len(MAGIC) + SYNC_LEN]
    (schema_len,) = _U32.unpack_from(head, len(MAGIC) + SYNC_LEN)
    schema = f.read(schema_len)
    if len(schema) < schema_len:
        raise FramedFormatError("truncated schema header")
    return FileHeader(sync, schema.decode("utf-8"),
                      _HDR_FIXED + schema_len)


def read_path_header(path: str) -> FileHeader:
    with sopen(path, buffer_size=1 << 16) as f:   # header-sized probe
        return read_header(f)


class FramedWriter:
    """Blocked writer (the DataFileWriter analog). ``schema`` is any JSON-
    serializable description of the records — the schema channel carries
    it verbatim to readers."""

    def __init__(self, path_or_file, schema: dict | str | None = None,
                 block_bytes: int = DEFAULT_BLOCK_BYTES,
                 sync: bytes | None = None) -> None:
        if isinstance(path_or_file, (str, os.PathLike)):
            self._f: BinaryIO = open(path_or_file, "wb")
            self._owns = True
        else:
            self._f = path_or_file
            self._owns = False
        self.sync = sync if sync is not None else secrets.token_bytes(SYNC_LEN)
        if len(self.sync) != SYNC_LEN:
            raise ValueError(f"sync marker must be {SYNC_LEN} bytes")
        schema_json = (schema if isinstance(schema, str)
                       else json.dumps(schema or {}))
        sj = schema_json.encode("utf-8")
        self._f.write(MAGIC + self.sync + _U32.pack(len(sj)) + sj)
        self._block: list[bytes] = []
        self._block_bytes = 0
        self._target = max(1, block_bytes)
        self.records_written = 0
        self.bytes_written = _HDR_FIXED + len(sj)

    @property
    def total_bytes(self) -> int:
        """Bytes written plus the still-buffered block (size accounting for
        callers chunking output, e.g. spill-mode max_bytes)."""
        pending = (SYNC_LEN + 8 + self._block_bytes) if self._block else 0
        return self.bytes_written + pending

    def append(self, record: bytes) -> None:
        self._block.append(record)
        self._block_bytes += 4 + len(record)
        self.records_written += 1
        if self._block_bytes >= self._target:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._block:
            return
        payload = b"".join(_U32.pack(len(r)) + r for r in self._block)
        self._f.write(self.sync + _U32.pack(len(self._block))
                      + _U32.pack(len(payload)) + payload)
        self.bytes_written += SYNC_LEN + 8 + len(payload)
        self._block.clear()
        self._block_bytes = 0

    def close(self) -> None:
        self._flush_block()
        self._f.flush()
        if self._owns:
            self._f.close()

    def __enter__(self) -> "FramedWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _find_sync(f: BinaryIO, sync: bytes, start: int,
               limit: int) -> int:
    """Position of the first sync marker starting at or after ``start`` and
    strictly before ``limit``, or -1. Reads in chunks, keeping a SYNC_LEN-1
    byte overlap so markers straddling chunk boundaries are found."""
    f.seek(start)
    buf = b""
    base = start                   # file position of buf[0]
    while base < limit:
        data = f.read(1 << 16)
        if not data:
            return -1
        buf += data
        idx = buf.find(sync)
        if idx != -1:
            found = base + idx
            return found if found < limit else -1
        keep = SYNC_LEN - 1
        base += len(buf) - keep
        buf = buf[-keep:]
    return -1


def iter_segment_records(path: str, offset: int,
                         length: int) -> Iterator[bytes]:
    """Records of every block whose sync starts inside [offset, offset+len)
    — the Python engine's framed arm (the C++ engine mirrors this)."""
    with sopen(path) as f:
        header = read_header(f)
        end = offset + length
        pos = max(offset, header.data_start)
        if pos >= end:
            return
        pos = _find_sync(f, header.sync, pos, end)
        while pos != -1 and pos < end:
            f.seek(pos)
            marker = f.read(SYNC_LEN)
            hdr = f.read(8)
            if marker != header.sync or len(hdr) < 8:
                raise FramedFormatError(
                    f"corrupt block header at {path}:{pos}")
            (count,) = _U32.unpack_from(hdr, 0)
            (size,) = _U32.unpack_from(hdr, 4)
            if count > MAX_BLOCK_RECORDS or size > MAX_BLOCK_BYTES:
                raise FramedFormatError(
                    f"implausible block at {path}:{pos} "
                    f"(count={count}, size={size})")
            payload = f.read(size)
            if len(payload) < size:
                raise FramedFormatError(f"truncated block at {path}:{pos}")
            view = memoryview(payload)
            p = 0
            for _ in range(count):
                if p + 4 > size:
                    raise FramedFormatError(
                        f"corrupt block payload at {path}:{pos}")
                (rlen,) = _U32.unpack_from(view, p)
                p += 4
                if p + rlen > size:
                    raise FramedFormatError(
                        f"corrupt record length at {path}:{pos}")
                yield bytes(view[p:p + rlen])
                p += rlen
            pos += SYNC_LEN + 8 + size    # blocks are back-to-back
            if pos >= end:
                break      # bytes past the split end belong to a later split
            # within our split, the next marker must start exactly here
            probe = f.read(SYNC_LEN)
            if not probe:
                break              # clean EOF after the previous block
            if len(probe) < SYNC_LEN:
                # a 1..15-byte tail is a writer that died mid-marker (or
                # mid-block) — fail loudly, exactly like the native engine
                raise FramedFormatError(
                    f"truncated sync marker at {path}:{pos}")
            if probe != header.sync:
                raise FramedFormatError(
                    f"lost sync after block at {path}:{pos}")


def iter_file_records(path: str) -> Iterator[bytes]:
    """All records of a framed file (spill-file consumption)."""
    size = ssize(path)
    yield from iter_segment_records(path, 0, size)
