"""Global byte-range split math for sharded file reading.

Rebuild of the reference's split algorithm (reference: tony-core/src/main/
java/com/linkedin/tony/io/HdfsAvroFileSplitReader.java:286-297
``computeReadSplitStart``/``computeReadSplitLength``): the byte ranges of all
input files are concatenated conceptually into one [0, total) range; task
``idx`` of ``n`` owns the contiguous range ``[idx*total/n, (idx+1)*total/n)``.
The splits tile the total exactly — no gaps, no overlap — which is the
property the reference's ``TestReader.java:42-60`` asserts and
``tests/test_io.py`` re-asserts here.

A record straddling a split boundary belongs to the split where it *starts*;
readers sync forward to the first record boundary at-or-after their offset
and read past their end to finish the final record (the reference does the
same with Avro block sync markers, ``:242``).
"""

from __future__ import annotations


from tony_tpu.storage import ssize
from dataclasses import dataclass


def split_start(total: int, idx: int, n: int) -> int:
    """Start of split ``idx`` of ``n`` over ``total`` bytes
    (reference: computeReadSplitStart:286)."""
    if not 0 <= idx < n:
        raise ValueError(f"idx {idx} out of range for {n} splits")
    return idx * total // n

def split_length(total: int, idx: int, n: int) -> int:
    """Length of split ``idx`` (reference: computeReadSplitLength:292).
    Defined so that splits tile [0, total) exactly."""
    if not 0 <= idx < n:
        raise ValueError(f"idx {idx} out of range for {n} splits")
    return (idx + 1) * total // n - idx * total // n


@dataclass(frozen=True)
class FileSegment:
    """A per-file byte range owned by one task
    (reference: createReadInfo:379 builds the per-file (offset,len) list)."""
    path: str
    offset: int
    length: int


def compute_read_info(paths: list[str], idx: int, n: int,
                      sizes: list[int] | None = None) -> list[FileSegment]:
    """Map the global split of task ``idx``/``n`` onto per-file segments.

    ``sizes`` may be passed to avoid re-statting (e.g. remote listings);
    otherwise each path is statted through the storage seam (``ssize``),
    so ``gs://`` inputs split exactly like local ones.
    """
    if sizes is None:
        sizes = [ssize(p) for p in paths]
    if len(sizes) != len(paths):
        raise ValueError("paths and sizes length mismatch")
    total = sum(sizes)
    start = split_start(total, idx, n)
    length = split_length(total, idx, n)
    segments: list[FileSegment] = []
    file_start = 0
    for path, size in zip(paths, sizes):
        file_end = file_start + size
        seg_start = max(start, file_start)
        seg_end = min(start + length, file_end)
        if seg_start < seg_end:
            segments.append(FileSegment(path, seg_start - file_start,
                                        seg_end - seg_start))
        file_start = file_end
    return segments


def full_records_in_split(paths: list[str], idx: int, n: int,
                          record_size: int,
                          sizes: list[int] | None = None) -> int:
    """Number of FULL fixed-size records task ``idx`` of ``n`` will read.

    Deterministic from file sizes alone, so every process can compute every
    other process's count without communication — the basis for SPMD
    batch-count agreement in :func:`tony_tpu.io.jax_feed.global_batches`
    (all processes must run the same number of jitted steps or multi-host
    training deadlocks). Short tail records (file size not a multiple of
    ``record_size``) are excluded, matching the feed's filtering.
    """
    if record_size <= 0:
        raise ValueError("full_records_in_split requires fixed-size framing")
    if sizes is None:
        sizes = [ssize(p) for p in paths]
    size_of = dict(zip(paths, sizes))
    count = 0
    for seg in compute_read_info(paths, idx, n, sizes=sizes):
        first = -(-seg.offset // record_size)
        end_excl = -(-(seg.offset + seg.length) // record_size)
        full_end = min(end_excl, size_of[seg.path] // record_size)
        count += max(0, full_end - first)
    return count
