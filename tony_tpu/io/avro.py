"""Avro object-container ingestion: read existing Avro datasets in place.

The reference's data feed is Avro-native (reference: tony-core/src/main/java/
com/linkedin/tony/io/HdfsAvroFileSplitReader.java): users point the job at
Avro files and every task reads its byte-range split, scanning forward to the
block sync marker (:242) and serving the schema over a side channel (:446).
This module gives the TPU build the same in-place capability — no ``tony
convert`` step — with a self-contained implementation of the Avro spec's
binary encoding and object-container format (https://avro.apache.org/docs/
current/specification/): no avro/fastavro dependency.

Container layout::

    magic      4 bytes   b"Obj\\x01"
    metadata   map<string, bytes>  (avro.schema json, avro.codec)
    sync       16 bytes  random per file
    blocks, repeating until EOF:
        count  zigzag varlong   records in this block
        size   zigzag varlong   serialized (possibly compressed) byte count
        data   size bytes
        sync   16 bytes

Split semantics (the convention the reference inherits from Avro's
DataFileReader.sync/pastSync): a reader seeks to its split offset, scans
forward to the next sync marker, and consumes blocks whose first data byte
lies at or before the split end — so every block belongs to exactly one
split and a block straddling the boundary goes to the split where it starts.

Codecs: ``null``, ``deflate`` (raw zlib, RFC 1951 — the two the spec
requires), and ``snappy`` (optional per spec but ubiquitous in real
datasets; pure-Python raw-format codec in :mod:`tony_tpu.io.snappy`,
framed per Avro's convention as compressed bytes + 4-byte big-endian
CRC32 of the uncompressed block). Unknown codecs still fail loudly
rather than mis-read.

Record boundaries inside a block are schema-driven (Avro records carry no
length prefix), so :func:`skip_datum` walks the schema to slice per-record
bytes — the unit the FileSplitReader contract serves. :func:`read_datum`
decodes to Python values for consumers that want structured rows.
"""

from __future__ import annotations

import json
import os
import secrets
import struct
import zlib
from typing import Any, BinaryIO, Iterator

from tony_tpu.io import snappy
from tony_tpu.storage import sopen, ssize

# chunked scan-with-overlap marker search — both formats use 16-byte random
# sync markers, so the framed implementation is reused verbatim
from tony_tpu.io.framed import _find_sync

MAGIC = b"Obj\x01"
SYNC_LEN = 16
_PRIMITIVES = frozenset(
    ("null", "boolean", "int", "long", "float", "double", "bytes", "string"))


class AvroFormatError(ValueError):
    pass


# ---------------------------------------------------------------------------
# zigzag varints (the long/int wire format)
# ---------------------------------------------------------------------------

def _read_long(buf: memoryview, pos: int) -> tuple[int, int]:
    shift, acc = 0, 0
    while True:
        if pos >= len(buf):
            raise AvroFormatError("truncated varint")
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise AvroFormatError("varint too long")
    return (acc >> 1) ^ -(acc & 1), pos


def _write_long(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_long_io(f: BinaryIO) -> int:
    shift, acc = 0, 0
    while True:
        c = f.read(1)
        if not c:
            raise AvroFormatError("truncated varint")
        b = c[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise AvroFormatError("varint too long")
    return (acc >> 1) ^ -(acc & 1)


# ---------------------------------------------------------------------------
# schema resolution (names registry for record/enum/fixed back-references)
# ---------------------------------------------------------------------------

def _fullname(schema: dict, namespace: str | None) -> str:
    name = schema["name"]
    if "." in name:
        return name
    ns = schema.get("namespace", namespace)
    return f"{ns}.{name}" if ns else name


def resolve_schema(schema: Any, names: dict[str, Any] | None = None,
                   namespace: str | None = None) -> Any:
    """Normalize a parsed-JSON schema: register named types so later
    string references ("com.x.Rec") resolve, and sanity-check structure.
    Returns the schema with named types registered in ``names``."""
    if names is None:
        names = {}
    if isinstance(schema, str):
        if schema in _PRIMITIVES:
            return schema
        full = (schema if "." in schema or not namespace
                else f"{namespace}.{schema}")
        if full in names:
            return names[full]
        if schema in names:
            return names[schema]
        raise AvroFormatError(f"unknown type reference {schema!r}")
    if isinstance(schema, list):                      # union
        return [resolve_schema(s, names, namespace) for s in schema]
    if not isinstance(schema, dict) or "type" not in schema:
        raise AvroFormatError(f"malformed schema node: {schema!r}")
    t = schema["type"]
    if t in _PRIMITIVES and len(schema) == 1:
        return t
    if t in ("record", "error"):
        full = _fullname(schema, namespace)
        names[full] = schema
        names.setdefault(schema["name"], schema)
        ns = schema.get("namespace", namespace)
        for field in schema.get("fields", ()):
            field["type"] = resolve_schema(field["type"], names, ns)
        return schema
    if t in ("enum", "fixed"):
        full = _fullname(schema, namespace)
        names[full] = schema
        names.setdefault(schema["name"], schema)
        if t == "fixed" and not (isinstance(schema.get("size"), int)
                                 and schema["size"] >= 0):
            raise AvroFormatError(f"fixed type needs a non-negative "
                                  f"integer size: {schema!r}")
        return schema
    if t == "array":
        schema["items"] = resolve_schema(schema["items"], names, namespace)
        return schema
    if t == "map":
        schema["values"] = resolve_schema(schema["values"], names, namespace)
        return schema
    if t in _PRIMITIVES:                              # {"type": "string"}
        return t
    if isinstance(t, (dict, list)):                   # nested/union type
        return resolve_schema(t, names, namespace)
    raise AvroFormatError(f"unsupported schema type {t!r}")


def parse_schema(schema_json: str) -> Any:
    return resolve_schema(json.loads(schema_json))


# ---------------------------------------------------------------------------
# datum walk: skip (boundary find), read (decode), write (encode)
# ---------------------------------------------------------------------------

def _type_of(schema: Any) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def skip_datum(schema: Any, buf: memoryview, pos: int) -> int:
    """Advance ``pos`` past one datum of ``schema`` — the record-boundary
    finder that lets a block of back-to-back records be sliced without full
    decoding of leaf values."""
    t = _type_of(schema)
    if t == "null":
        return pos
    if t == "boolean":
        return pos + 1
    if t in ("int", "long"):
        _, pos = _read_long(buf, pos)
        return pos
    if t == "float":
        return pos + 4
    if t == "double":
        return pos + 8
    if t in ("bytes", "string"):
        n, pos = _read_long(buf, pos)
        if n < 0 or pos + n > len(buf):
            raise AvroFormatError(f"bad {t} length {n}")
        return pos + n
    if t == "fixed":
        return pos + schema["size"]
    if t == "enum":
        _, pos = _read_long(buf, pos)
        return pos
    if t == "union":
        idx, pos = _read_long(buf, pos)
        if not 0 <= idx < len(schema):
            raise AvroFormatError(f"union index {idx} out of range")
        return skip_datum(schema[idx], buf, pos)
    if t == "record" or t == "error":
        for field in schema["fields"]:
            pos = skip_datum(field["type"], buf, pos)
        return pos
    if t == "array" or t == "map":
        while True:
            count, pos = _read_long(buf, pos)
            if count == 0:
                return pos
            if count < 0:       # block with explicit byte size: skip whole
                size, pos = _read_long(buf, pos)
                if size < 0 or pos + size > len(buf):
                    raise AvroFormatError("bad array/map block size")
                pos += size
                continue
            for _ in range(count):
                if t == "array":
                    pos = skip_datum(schema["items"], buf, pos)
                else:
                    n, pos = _read_long(buf, pos)       # key (string)
                    if n < 0 or pos + n > len(buf):
                        raise AvroFormatError(f"bad map key length {n}")
                    pos += n
                    pos = skip_datum(schema["values"], buf, pos)
    raise AvroFormatError(f"unsupported type {t!r}")


def read_datum(schema: Any, buf: memoryview, pos: int) -> tuple[Any, int]:
    """Decode one datum → (python value, new position)."""
    t = _type_of(schema)
    if t == "null":
        return None, pos
    if t == "boolean":
        return buf[pos] != 0, pos + 1
    if t in ("int", "long"):
        return _read_long(buf, pos)
    if t == "float":
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if t == "double":
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if t in ("bytes", "string"):
        n, pos = _read_long(buf, pos)
        if n < 0 or pos + n > len(buf):
            raise AvroFormatError(f"bad {t} length {n}")
        raw = bytes(buf[pos:pos + n])
        return (raw.decode("utf-8") if t == "string" else raw), pos + n
    if t == "fixed":
        n = schema["size"]
        return bytes(buf[pos:pos + n]), pos + n
    if t == "enum":
        idx, pos = _read_long(buf, pos)
        symbols = schema["symbols"]
        if not 0 <= idx < len(symbols):
            raise AvroFormatError(f"enum index {idx} out of range")
        return symbols[idx], pos
    if t == "union":
        idx, pos = _read_long(buf, pos)
        if not 0 <= idx < len(schema):
            raise AvroFormatError(f"union index {idx} out of range")
        return read_datum(schema[idx], buf, pos)
    if t == "record" or t == "error":
        out = {}
        for field in schema["fields"]:
            out[field["name"]], pos = read_datum(field["type"], buf, pos)
        return out, pos
    if t == "array":
        items = []
        while True:
            count, pos = _read_long(buf, pos)
            if count == 0:
                return items, pos
            if count < 0:
                count = -count
                _, pos = _read_long(buf, pos)      # byte size: unused here
            for _ in range(count):
                v, pos = read_datum(schema["items"], buf, pos)
                items.append(v)
    if t == "map":
        out = {}
        while True:
            count, pos = _read_long(buf, pos)
            if count == 0:
                return out, pos
            if count < 0:
                count = -count
                _, pos = _read_long(buf, pos)
            for _ in range(count):
                n, pos = _read_long(buf, pos)
                if n < 0 or pos + n > len(buf):
                    raise AvroFormatError(f"bad map key length {n}")
                key = bytes(buf[pos:pos + n]).decode("utf-8")
                pos += n
                out[key], pos = read_datum(schema["values"], buf, pos)
    raise AvroFormatError(f"unsupported type {t!r}")


def write_datum(schema: Any, value: Any, out: bytearray) -> None:
    """Encode one datum (the fixture/convert writer — exact inverse of
    :func:`read_datum`)."""
    t = _type_of(schema)
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if value else 0)
        return
    if t in ("int", "long"):
        out += _write_long(int(value))
        return
    if t == "float":
        out += struct.pack("<f", value)
        return
    if t == "double":
        out += struct.pack("<d", value)
        return
    if t in ("bytes", "string"):
        raw = value.encode("utf-8") if t == "string" else bytes(value)
        out += _write_long(len(raw)) + raw
        return
    if t == "fixed":
        raw = bytes(value)
        if len(raw) != schema["size"]:
            raise AvroFormatError(
                f"fixed value of {len(raw)} bytes != size {schema['size']}")
        out += raw
        return
    if t == "enum":
        out += _write_long(schema["symbols"].index(value))
        return
    if t == "union":
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                out += _write_long(i)
                write_datum(branch, value, out)
                return
        raise AvroFormatError(f"value {value!r} matches no union branch")
    if t == "record" or t == "error":
        for field in schema["fields"]:
            write_datum(field["type"], value[field["name"]], out)
        return
    if t == "array":
        if value:
            out += _write_long(len(value))
            for v in value:
                write_datum(schema["items"], v, out)
        out += _write_long(0)
        return
    if t == "map":
        if value:
            out += _write_long(len(value))
            for k, v in value.items():
                write_datum("string", k, out)
                write_datum(schema["values"], v, out)
        out += _write_long(0)
        return
    raise AvroFormatError(f"unsupported type {t!r}")


def _matches(schema: Any, value: Any) -> bool:
    t = _type_of(schema)
    if t == "null":
        return value is None
    if t == "boolean":
        return isinstance(value, bool)
    if t in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if t in ("float", "double"):
        return isinstance(value, float)
    if t == "string":
        return isinstance(value, str)
    if t in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if t == "enum":
        return isinstance(value, str) and value in schema["symbols"]
    if t in ("record", "error", "map"):
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, list)
    return False


# ---------------------------------------------------------------------------
# object container file: header, writer, split-aware block iteration
# ---------------------------------------------------------------------------

class AvroHeader:
    __slots__ = ("sync", "schema_json", "codec", "data_start", "schema")

    def __init__(self, sync: bytes, schema_json: str, codec: str,
                 data_start: int):
        self.sync = sync
        self.schema_json = schema_json
        self.codec = codec
        self.data_start = data_start
        self.schema = parse_schema(schema_json)


def is_avro_file(path: str) -> bool:
    """True when ``path`` starts with the Avro container magic (missing
    files raise OSError — same loud-typo policy as framed.is_framed_file)."""
    from tony_tpu.storage import storage_for
    return storage_for(path).read_range(path, 0, len(MAGIC)) == MAGIC


def read_header(f: BinaryIO) -> AvroHeader:
    f.seek(0)
    if f.read(len(MAGIC)) != MAGIC:
        raise AvroFormatError("not an Avro object container file")
    meta: dict[str, bytes] = {}
    while True:                                   # metadata map blocks
        count = _read_long_io(f)
        if count == 0:
            break
        if count < 0:
            count = -count
            _read_long_io(f)                      # block byte size
        for _ in range(count):
            klen = _read_long_io(f)
            key = f.read(klen).decode("utf-8")
            vlen = _read_long_io(f)
            meta[key] = f.read(vlen)
    sync = f.read(SYNC_LEN)
    if len(sync) != SYNC_LEN:
        raise AvroFormatError("truncated container header")
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate", "snappy"):
        raise AvroFormatError(
            f"unsupported avro codec {codec!r} (null, deflate, and "
            f"snappy are supported)")
    schema_json = meta.get("avro.schema", b"").decode("utf-8")
    if not schema_json:
        raise AvroFormatError("container missing avro.schema metadata")
    return AvroHeader(sync, schema_json, codec, f.tell())


def read_path_header(path: str) -> AvroHeader:
    with sopen(path, buffer_size=1 << 16) as f:   # header-sized probe
        return read_header(f)


class AvroWriter:
    """Container writer (DataFileWriter analog) — fixtures, ``tony
    convert --to avro``, and round-trip tests. Spec-conformant output:
    readable by any Avro implementation."""

    def __init__(self, path_or_file, schema: dict | str,
                 codec: str = "null", block_records: int = 1024,
                 sync: bytes | None = None) -> None:
        if isinstance(path_or_file, (str, os.PathLike)):
            self._f: BinaryIO = open(path_or_file, "wb")
            self._owns = True
        else:
            self._f = path_or_file
            self._owns = False
        if codec not in ("null", "deflate", "snappy"):
            raise AvroFormatError(f"unsupported codec {codec!r}")
        self._codec = codec
        schema_json = (schema if isinstance(schema, str)
                       else json.dumps(schema))
        self.schema = parse_schema(schema_json)
        self.sync = sync if sync is not None else secrets.token_bytes(SYNC_LEN)
        if len(self.sync) != SYNC_LEN:
            raise ValueError(f"sync marker must be {SYNC_LEN} bytes")
        meta = {"avro.schema": schema_json.encode("utf-8"),
                "avro.codec": codec.encode("utf-8")}
        self._f.write(MAGIC)
        self._f.write(_write_long(len(meta)))
        for k, v in meta.items():
            kb = k.encode("utf-8")
            self._f.write(_write_long(len(kb)) + kb
                          + _write_long(len(v)) + v)
        self._f.write(_write_long(0) + self.sync)
        self._buf = bytearray()
        self._count = 0
        self._block_records = max(1, block_records)
        self.records_written = 0

    def append(self, value: Any) -> None:
        write_datum(self.schema, value, self._buf)
        self._count += 1
        self.records_written += 1
        if self._count >= self._block_records:
            self._flush_block()

    def append_encoded(self, raw: bytes) -> None:
        """Append an already-encoded datum (split/merge tooling)."""
        self._buf += raw
        self._count += 1
        self.records_written += 1
        if self._count >= self._block_records:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._count:
            return
        data = bytes(self._buf)
        if self._codec == "deflate":
            data = zlib.compress(data)[2:-4]      # raw RFC-1951, per spec
        elif self._codec == "snappy":
            # Avro frames snappy blocks as compressed bytes + 4-byte
            # BIG-endian CRC32 of the uncompressed bytes
            data = (snappy.compress(data)
                    + (zlib.crc32(data) & 0xFFFFFFFF).to_bytes(4, "big"))
        self._f.write(_write_long(self._count) + _write_long(len(data))
                      + data + self.sync)
        self._buf.clear()
        self._count = 0

    def close(self) -> None:
        self._flush_block()
        self._f.flush()
        if self._owns:
            self._f.close()

    def __enter__(self) -> "AvroWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def iter_segment_blocks(path: str, offset: int, length: int,
                        header: AvroHeader | None = None,
                        ) -> Iterator[tuple[int, bytes]]:
    """(count, decompressed block bytes) for every block of the split —
    the reference's sync→pastSync walk (HdfsAvroFileSplitReader.java:242
    seeks the marker, then consumes blocks until the split end).

    Tiling rule: every block is preceded by a sync marker (the container
    header ends with one, and each block is followed by one), and a block
    belongs to the split in which its preceding marker STARTS — the same
    invariant as framed.py, so adjacent splits tile exactly: no record is
    read twice or skipped for any split geometry."""
    with sopen(path) as f:
        if header is None:
            header = read_header(f)
        end = offset + length
        # never scan inside the header (schema bytes aren't data); the
        # header's trailing sync at data_start-16 is block 1's marker
        scan_from = max(offset, header.data_start - SYNC_LEN)
        at = _find_sync(f, header.sync, scan_from, end)
        if at == -1:
            return
        pos = at + SYNC_LEN                   # first owned block's start
        while True:
            f.seek(pos)
            probe = f.read(1)
            if not probe:
                return                        # clean EOF after final sync
            f.seek(pos)
            count = _read_long_io(f)
            size = _read_long_io(f)
            if count < 0 or size < 0 or size > (1 << 31):
                raise AvroFormatError(
                    f"implausible block at {path}:{pos} "
                    f"(count={count}, size={size})")
            data = f.read(size)
            if len(data) < size:
                raise AvroFormatError(f"truncated block at {path}:{pos}")
            marker = f.read(SYNC_LEN)
            if len(marker) < SYNC_LEN or marker != header.sync:
                raise AvroFormatError(f"lost sync after block at {path}:{pos}")
            if header.codec == "deflate":
                data = zlib.decompress(data, -15)
            elif header.codec == "snappy":
                if len(data) < 4:
                    raise AvroFormatError(
                        f"snappy block at {path}:{pos} too short for CRC")
                crc = int.from_bytes(data[-4:], "big")
                try:
                    data = snappy.decompress(data[:-4])
                except snappy.SnappyError as e:
                    raise AvroFormatError(
                        f"corrupt snappy block at {path}:{pos}: {e}") from e
                if (zlib.crc32(data) & 0xFFFFFFFF) != crc:
                    raise AvroFormatError(
                        f"snappy CRC mismatch at {path}:{pos}")
            yield count, data
            pos = f.tell()                    # next block start
            if pos - SYNC_LEN >= end:
                return     # its marker starts in a later split — not ours


def iter_segment_records(path: str, offset: int,
                         length: int) -> Iterator[bytes]:
    """Raw encoded datum bytes of every record in the split's blocks — the
    FileSplitReader record contract (decode with read_datum + the schema
    from the reader's schema channel)."""
    header = read_path_header(path)
    for count, data in iter_segment_blocks(path, offset, length, header):
        view = memoryview(data)
        pos = 0
        for _ in range(count):
            new = skip_datum(header.schema, view, pos)
            if new > len(view):
                raise AvroFormatError(
                    f"record overruns block in {path} (pos {pos})")
            yield bytes(view[pos:new])
            pos = new
        if pos != len(view):
            raise AvroFormatError(
                f"block in {path} has {len(view) - pos} trailing bytes "
                f"after {count} records")


def iter_file_records(path: str) -> Iterator[bytes]:
    yield from iter_segment_records(path, 0, ssize(path))


def iter_file_values(path: str) -> Iterator[Any]:
    """Decoded Python values for every record (convenience consumption)."""
    header = read_path_header(path)
    for raw in iter_file_records(path):
        value, _ = read_datum(header.schema, memoryview(raw), 0)
        yield value
