"""Pure-Python snappy raw-format codec for Avro block (de)compression.

The reference reads whatever codec the Avro library decodes
(HdfsAvroFileSplitReader.java delegates block decode to ``DataFileReader``),
and real-world Avro datasets are very often snappy-compressed — so the
"read existing datasets in place" story needs snappy even though the Avro
spec lists it as optional. No snappy library is baked into the image; the
raw format (github.com/google/snappy/blob/main/format_description.txt) is
small enough to implement directly:

- preamble: uncompressed length, little-endian varint
- elements: tag byte, low 2 bits select the type —
  ``00`` literal (length in the upper 6 bits, or 60-63 → 1-4 extra
  little-endian length bytes, stored value = length - 1),
  ``01`` copy, 1-byte offset  (len 4-11 in bits 2-4, offset 11 bits),
  ``10`` copy, 2-byte offset  (len = upper 6 bits + 1, offset LE16),
  ``11`` copy, 4-byte offset  (len = upper 6 bits + 1, offset LE32)
- copies may overlap forward (offset < length ⇒ RLE-style repetition),
  which is why the decoder appends byte-ranges in a loop instead of one
  slice when the run overlaps.

The compressor is a greedy 4-byte-hash matcher — enough to emit real copy
elements (so round-trip tests exercise every decoder path, including
overlapping runs) and to shrink repetitive fixtures, not a performance
port. Avro's snappy codec frames each block as ``compressed bytes +
4-byte BIG-endian CRC32 of the uncompressed bytes``; that framing lives
in :mod:`tony_tpu.io.avro`, not here — this module is format-pure.
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint preamble")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint preamble overflow")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    """Decode one snappy raw-format stream."""
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                                   # literal
            ln = tag >> 2
            if ln >= 60:                                # 1-4 length bytes
                extra = ln - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            ln += 1
            if pos + ln > n:
                raise SnappyError("literal overruns input")
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:                                   # copy, 1-byte offset
            if pos >= n:
                raise SnappyError("truncated copy-1")
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                                 # copy, 2-byte offset
            if pos + 2 > n:
                raise SnappyError("truncated copy-2")
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                                           # copy, 4-byte offset
            if pos + 4 > n:
                raise SnappyError("truncated copy-4")
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise SnappyError(f"copy offset {off} outside window")
        start = len(out) - off
        while ln > 0:                                   # overlap-safe
            chunk = out[start:start + min(ln, off)]
            out += chunk
            start += len(chunk)
            ln -= len(chunk)
    if len(out) != expected:
        raise SnappyError(
            f"decompressed {len(out)} bytes, preamble promised {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, data: bytes, start: int, end: int) -> None:
    ln = end - start - 1
    if ln < 60:
        out.append(ln << 2)
    else:
        nbytes = (ln.bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += ln.to_bytes(nbytes, "little")
    out += data[start:end]


def compress(data: bytes) -> bytes:
    """Greedy single-pass snappy encoder (correct, not tuned)."""
    out = bytearray(_write_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    lit_start = 0
    i = 0
    while i + 4 <= n:
        key = data[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is None or i - cand > 0xFFFFFFFF:
            i += 1
            continue
        # extend the match
        ln = 4
        while i + ln < n and ln < 64 and data[cand + ln] == data[i + ln]:
            ln += 1
        if lit_start < i:
            _emit_literal(out, data, lit_start, i)
        off = i - cand
        if ln <= 11 and off < 2048:                     # copy-1
            out.append(1 | ((ln - 4) << 2) | ((off >> 8) << 5))
            out.append(off & 0xFF)
        elif off <= 0xFFFF:                             # copy-2
            out.append(2 | ((ln - 1) << 2))
            out += off.to_bytes(2, "little")
        else:                                           # copy-4
            out.append(3 | ((ln - 1) << 2))
            out += off.to_bytes(4, "little")
        i += ln
        lit_start = i
    if lit_start < n:
        _emit_literal(out, data, lit_start, n)
    return bytes(out)
