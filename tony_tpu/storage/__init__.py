"""Pluggable storage: one interface over local paths and ``gs://`` URIs.

The reference reaches every durable byte — staging uploads, history
files, localized resources — through Hadoop's ``FileSystem`` abstraction
(reference: TonyClient.java:163-192 staging, util/HdfsUtils.java scan/
read helpers, events/EventHandler.java HDFS writer). The TPU rebuild has
no HDFS; its two substrates are the local filesystem (laptop runs, the
local fake-cluster backend) and GCS (real TPU fleets, where slice hosts
share no filesystem with the submit host). This module is the one seam:
callers hold plain path strings (``/x/y`` or ``gs://bucket/x/y``) and the
scheme picks the implementation.

GCS is driven through the ``gsutil`` CLI rather than a client library —
the library is not in the image, the CLI is on every TPU VM, and a
subprocess boundary lets the test suite substitute a fake ``gsutil`` on
PATH (the same trick the reference's MiniDFS plays for HDFS). Override
the binary with ``TONY_GSUTIL``.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import subprocess
import threading

__all__ = [
    "Storage", "LocalStorage", "GcsStorage", "StorageError",
    "storage_for", "register_storage", "scheme_of",
    "sjoin", "sdirname", "sbasename", "is_remote",
    "sopen", "ssize",
]

_SCHEME_RE = re.compile(r"^([a-z][a-z0-9+.-]*)://")


class StorageError(OSError):
    """Backend (gsutil/...) operation failure. Subclasses OSError so
    callers guarding filesystem IO naturally cover remote storage too."""


def scheme_of(path: str) -> str:
    """'' for local paths, 'gs' for gs://... etc."""
    m = _SCHEME_RE.match(path)
    return m.group(1) if m else ""


def is_remote(path: str) -> bool:
    return bool(scheme_of(path))


def sjoin(base: str, *parts: str) -> str:
    """Path join that keeps URI schemes intact (os.path.join would treat
    'gs://b' fine on posix, but be explicit and platform-independent)."""
    if is_remote(base):
        out = base.rstrip("/")
        for p in parts:
            out += "/" + p.strip("/")
        return out
    return os.path.join(base, *parts)


def sdirname(path: str) -> str:
    if is_remote(path):
        scheme, _, rest = path.partition("://")
        head, _, _ = rest.rstrip("/").rpartition("/")
        return f"{scheme}://{head}"
    return os.path.dirname(path)


def sbasename(path: str) -> str:
    if is_remote(path):
        return path.rstrip("/").rpartition("/")[2]
    return os.path.basename(path)


class Storage:
    """Operations every substrate must provide. Paths are scheme-qualified
    strings; directory semantics are emulated where the substrate has none
    (GCS: a 'directory' exists iff some object lives under it)."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> list[str]:
        """Immediate child names (files and dirs)."""
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def walk_files(self, path: str):
        """Yield (dirpath, [filenames]) over the whole tree, like os.walk
        restricted to files (reference: HdfsUtils.getJobFolders:123)."""
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def read_tail(self, path: str, n: int) -> bytes:
        """Last n bytes (history server reads only jhist tails)."""
        raise NotImplementedError

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """``length`` bytes starting at ``offset`` (short read at EOF) —
        the data feed's block-fetch primitive (the reference reads the
        distributed filesystem in place: HdfsAvroFileSplitReader.java:201
        ``fs.open(inputPath)`` + positioned reads)."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        """Object size in bytes (split math needs it without a download)."""
        raise NotImplementedError

    def open_read(self, path: str, buffer_size: int | None = None):
        """Binary seekable read stream. Local paths get the real file;
        remote substrates get a buffered ranged reader — the data feed's
        sync-scan and block walk run against storage directly, no
        pre-copy. ``buffer_size`` tunes the remote fetch granularity:
        header/magic probes pass a small one so a few-byte peek doesn't
        pull a full scan-sized chunk."""
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def open_append(self, path: str):
        """Text-mode append stream. flush() makes the bytes visible to
        readers (possibly by re-uploading the object on GCS)."""
        raise NotImplementedError

    def move(self, src: str, dst: str) -> None:
        """Rename within this storage (the .inprogress -> final publish)."""
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def put(self, local_path: str, path: str) -> None:
        """Upload one local file."""
        raise NotImplementedError

    def get(self, path: str, local_path: str) -> None:
        """Download one file to a local path."""
        raise NotImplementedError

    def put_tree(self, local_dir: str, path: str) -> None:
        """Upload a local directory tree (client staging)."""
        raise NotImplementedError

    def get_tree(self, path: str, local_dir: str) -> None:
        """Download a tree (executor-side localization)."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
class LocalStorage(Storage):
    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str) -> list[str]:
        return sorted(os.listdir(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def walk_files(self, path: str):
        for root, _, files in os.walk(path):
            yield root, files

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def read_tail(self, path: str, n: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read()

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def size(self, path: str) -> int:
        return os.path.getsize(path)

    def open_read(self, path: str, buffer_size: int | None = None):
        return open(path, "rb")

    def write_bytes(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def open_append(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return open(path, "a", encoding="utf-8")

    def move(self, src: str, dst: str) -> None:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        os.replace(src, dst)

    def remove(self, path: str) -> None:
        os.remove(path)

    def put(self, local_path: str, path: str) -> None:
        if os.path.abspath(local_path) != os.path.abspath(path):
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            shutil.copy2(local_path, path)

    def get(self, path: str, local_path: str) -> None:
        self.put(path, local_path)

    def put_tree(self, local_dir: str, path: str) -> None:
        if os.path.abspath(local_dir) != os.path.abspath(path):
            shutil.copytree(local_dir, path, dirs_exist_ok=True)

    def get_tree(self, path: str, local_dir: str) -> None:
        self.put_tree(path, local_dir)


# ---------------------------------------------------------------------------
class _GcsAppendStream(io.TextIOBase):
    """GCS objects are immutable — append is emulated by buffering the whole
    stream and re-uploading on flush. Event traffic is control-plane-rate
    (a handful of task lifecycle records per job), so whole-object rewrite
    per flush is cheap and keeps .inprogress files live-readable, matching
    the reference's HDFS append visibility."""

    def __init__(self, storage: "GcsStorage", path: str) -> None:
        super().__init__()
        self._storage = storage
        self._path = path
        self._buf: list[str] = []
        self._lock = threading.Lock()
        if storage.exists(path):
            self._buf.append(storage.read_bytes(path).decode("utf-8"))

    def write(self, s: str) -> int:
        with self._lock:
            self._buf.append(s)
        return len(s)

    def flush(self) -> None:
        with self._lock:
            data = "".join(self._buf).encode("utf-8")
        self._storage.write_bytes(self._path, data)

    def close(self) -> None:
        if not self.closed:
            self.flush()
        super().close()


class _GcsRangedReader(io.RawIOBase):
    """Seekable raw stream over ranged GCS reads. Wrapped in a
    ``BufferedReader`` by :meth:`GcsStorage.open_read`, which turns the
    data feed's byte-at-a-time parsing into chunk-sized ``readinto``
    calls — one gsutil invocation per ~4 MB of sequential scan.

    Sequential scans additionally PREFETCH: scan-sized reads (>= one
    READ_CHUNK) keep a window of ``depth`` chunk fetches in flight on a
    thread pool, so a TPU-rate consumer is not gated on one serial gsutil
    fork per chunk (the reference's DataFetcher thread overlapped reads
    the same way against its HDFS client,
    HdfsAvroFileSplitReader.java:176 — here each fetch is a subprocess,
    so overlap needs N of them). Small reads (header/magic probes through
    a small ``buffer_size``) bypass the window and fetch exactly what was
    asked. Memory bound: depth x READ_CHUNK."""

    def __init__(self, storage: "GcsStorage", path: str,
                 depth: int | None = None) -> None:
        super().__init__()
        self._storage = storage
        self._path = path
        self._pos = 0
        self._size = storage.size(path)
        self._depth = storage.prefetch_depth if depth is None else depth
        self._futures: dict[int, object] = {}    # chunk index -> Future
        self._pool = None

    def _chunk_future(self, j: int, c: int):
        fut = self._futures.get(j)
        if fut is None:
            fut = self._pool.submit(self._storage.read_range, self._path,
                                    j * c, min(c, self._size - j * c))
            self._futures[j] = fut
        return fut

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            pos = offset
        elif whence == os.SEEK_CUR:
            pos = self._pos + offset
        elif whence == os.SEEK_END:
            pos = self._size + offset
        else:
            raise ValueError(f"bad whence {whence}")
        if pos < 0:
            # validate BEFORE committing: a caught failed seek must not
            # leave the stream at a negative position (a negative offset
            # would read gsutil's tail syntax, silently wrong bytes)
            raise OSError("negative seek position")
        self._pos = pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readinto(self, b) -> int:
        if self._pos >= self._size:
            return 0
        n = min(len(b), self._size - self._pos)
        c = self._storage.READ_CHUNK
        if self._depth <= 1 or len(b) < c:
            # serial path: probes and depth-1 configs fetch exactly n
            data = self._storage.read_range(self._path, self._pos, n)
            b[:len(data)] = data
            self._pos += len(data)
            return len(data)
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._depth,
                thread_name_prefix="tony-gcs-prefetch")
        i = self._pos // c
        last = (self._size - 1) // c
        # evict chunks behind the cursor or beyond the window (seeks);
        # cancel() is best-effort — a running fetch just gets discarded
        for j in list(self._futures):
            if j < i or j >= i + self._depth:
                self._futures.pop(j).cancel()
        for j in range(i, min(i + self._depth, last + 1)):
            self._chunk_future(j, c)
        data = self._chunk_future(i, c).result()
        start = self._pos - i * c
        out = data[start:start + n]       # serve from chunk i only; the
        if start + len(out) >= len(data):  # BufferedReader loops on short
            self._futures.pop(i, None)     # reads
        b[:len(out)] = out
        self._pos += len(out)
        return len(out)

    def close(self) -> None:
        for fut in self._futures.values():
            fut.cancel()
        self._futures.clear()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        super().close()


class GcsStorage(Storage):
    """``gs://`` via the gsutil CLI (override binary with $TONY_GSUTIL)."""

    #: every gsutil call is bounded — a network blackhole must never hang
    #: coordinator teardown or a history-server request forever (override
    #: with $TONY_GSUTIL_TIMEOUT, seconds)
    DEFAULT_TIMEOUT_S = 600.0

    def __init__(self, gsutil: str | None = None,
                 timeout_s: float | None = None,
                 token: str | None = None) -> None:
        self.gsutil = gsutil or os.environ.get("TONY_GSUTIL") or "gsutil"
        self.timeout_s = timeout_s if timeout_s is not None else float(
            os.environ.get("TONY_GSUTIL_TIMEOUT", self.DEFAULT_TIMEOUT_S))
        #: per-job scoped credential (tony.gcs.service-account): an
        #: explicit token, else $TONY_GCS_TOKEN read per call — the env
        #: var is how the client hands the job identity to coordinator
        #: and executors without any byte of it touching the bucket
        self.token = token
        self._size_cache: dict[str, tuple[int, float]] = {}
        #: concurrent ranged fetches per open_read stream (sequential-scan
        #: prefetch window); 1 disables the pool entirely
        self.prefetch_depth = max(1, int(
            os.environ.get("TONY_GCS_PREFETCH_DEPTH", "4")))

    # -- plumbing ----------------------------------------------------------
    def _env(self, args: tuple = ()) -> dict[str, str] | None:
        """Subprocess env: inject the job's scoped token (gcloud-suite
        tools honor CLOUDSDK_AUTH_ACCESS_TOKEN over ambient credentials);
        None → inherit, keeping the ambient-credential default. A token
        FILE wins over the env value — it is re-read per call, so
        client-pushed renewals (executor heartbeat republishing) reach
        processes that forked before the renewal.

        The credential may be a JSON ``{bucket: token}`` blob
        (multi-identity jobs, ``tony.gcs.service-account`` with
        ``bucket=sa`` pairs — the list-valued ``tony.other.namenodes``
        analog): the token is then selected by this CALL's target bucket
        (first gs:// argument), ``*`` as the fallback identity. A bucket
        with no mapped identity is a configuration error and raises —
        silently falling back to ambient credentials would defeat the
        per-job identity scoping."""
        tok = self.token
        if not tok:
            tok_file = os.environ.get("TONY_GCS_TOKEN_FILE")
            if tok_file:
                try:
                    with open(tok_file, encoding="utf-8") as f:
                        tok = f.read().strip()
                except OSError:
                    tok = None
        if not tok:
            tok = os.environ.get("TONY_GCS_TOKEN")
        if not tok:
            return None
        if tok.lstrip().startswith("{"):
            try:
                mapping = json.loads(tok)
            except ValueError:
                mapping = None
            if isinstance(mapping, dict):
                buckets = {a[len("gs://"):].split("/", 1)[0]
                           for a in args
                           if isinstance(a, str) and a.startswith("gs://")}
                toks = set()
                for bucket in buckets or {""}:
                    t = mapping.get(bucket) or mapping.get("*")
                    if not t:
                        raise StorageError(
                            f"no GCS identity mapped for bucket "
                            f"{bucket!r} (tony.gcs.service-account lists "
                            f"{sorted(mapping)}; add '{bucket}=sa' or a "
                            f"'*=sa' default)")
                    toks.add(t)
                if len(toks) > 1:
                    # a single gsutil call runs under ONE identity; a
                    # cross-bucket op spanning two would silently act on
                    # the second bucket as the first's identity — make
                    # the caller copy through read+write instead
                    raise StorageError(
                        f"one call touches buckets {sorted(buckets)} "
                        f"mapped to DIFFERENT identities; split the "
                        f"operation per bucket")
                tok = toks.pop()
        return {**os.environ, "CLOUDSDK_AUTH_ACCESS_TOKEN": tok}

    def _run(self, *args: str, input_bytes: bytes | None = None,
             ok_codes: tuple[int, ...] = (0,)) -> bytes:
        try:
            proc = subprocess.run(
                [self.gsutil, "-q", *args], input=input_bytes,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=self._env(args), timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise StorageError(
                f"{self.gsutil} {' '.join(args)} timed out after "
                f"{self.timeout_s:.0f}s") from e
        if proc.returncode not in ok_codes:
            raise StorageError(
                f"{self.gsutil} {' '.join(args)} failed rc={proc.returncode}: "
                f"{proc.stderr.decode('utf-8', 'replace').strip()}")
        return proc.stdout

    def _try(self, *args: str) -> bool:
        """False means the probed object genuinely is not there; a timeout
        is a backend failure and raises — silently reading a blackhole as
        'does not exist' could make callers overwrite live data."""
        try:
            proc = subprocess.run(
                [self.gsutil, "-q", *args],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=self._env(args), timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise StorageError(
                f"{self.gsutil} {' '.join(args)} timed out after "
                f"{self.timeout_s:.0f}s") from e
        return proc.returncode == 0

    def _ls(self, pattern: str) -> list[str]:
        """[] means nothing matches; a timeout raises (see _try)."""
        try:
            proc = subprocess.run(
                [self.gsutil, "-q", "ls", pattern],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                env=self._env(("ls", pattern)), timeout=self.timeout_s)
        except subprocess.TimeoutExpired as e:
            raise StorageError(
                f"{self.gsutil} ls {pattern} timed out after "
                f"{self.timeout_s:.0f}s") from e
        if proc.returncode != 0:
            return []
        return [l.strip() for l in proc.stdout.decode().splitlines()
                if l.strip()]

    # -- interface ---------------------------------------------------------
    def exists(self, path: str) -> bool:
        # stat matches objects; a trailing-slash ls matches "directories"
        return self._try("stat", path) or bool(
            self._ls(path.rstrip("/") + "/"))

    def isdir(self, path: str) -> bool:
        return bool(self._ls(path.rstrip("/") + "/"))

    def listdir(self, path: str) -> list[str]:
        names = set()
        for entry in self._ls(path.rstrip("/") + "/"):
            name = entry[len(path.rstrip("/")) + 1:] if entry.startswith(
                path.rstrip("/")) else sbasename(entry)
            names.add(name.strip("/").split("/")[0] if name else "")
        names.discard("")
        return sorted(names)

    def makedirs(self, path: str) -> None:
        pass    # GCS has no directories; objects create their prefixes

    def walk_files(self, path: str):
        root = path.rstrip("/")
        by_dir: dict[str, list[str]] = {}
        for entry in self._ls(root + "/**"):
            if entry.endswith("/"):
                continue
            by_dir.setdefault(sdirname(entry), []).append(sbasename(entry))
        for d in sorted(by_dir):
            yield d, sorted(by_dir[d])

    def read_bytes(self, path: str) -> bytes:
        return self._run("cat", path)

    def read_tail(self, path: str, n: int) -> bytes:
        return self._run("cat", "-r", f"-{n}", path)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        if length <= 0:
            return b""
        # gsutil cat -r takes an INCLUSIVE byte range; an end past EOF is
        # clamped by the tool, a start at/after EOF yields empty output
        return self._run("cat", "-r", f"{offset}-{offset + length - 1}",
                         path)

    #: stat results are cached briefly: the data feed sizes, sniffs, and
    #: re-opens the same objects several times during reader setup, and
    #: each miss is a gsutil subprocess (hundreds of ms over a slow
    #: tunnel). GCS objects are immutable per generation, so the only
    #: staleness risk is an object REPLACED mid-read — bounded to this
    #: window. Set 0 to disable.
    SIZE_CACHE_TTL_S = 30.0

    def size(self, path: str) -> int:
        import time as _time
        now = _time.monotonic()
        hit = self._size_cache.get(path)
        if hit is not None and now - hit[1] < self.SIZE_CACHE_TTL_S:
            return hit[0]
        out = self._run("du", path).decode("utf-8", "replace")
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 2 and parts[0].isdigit():
                if len(self._size_cache) > 4096:
                    self._size_cache.clear()
                self._size_cache[path] = (int(parts[0]), now)
                return int(parts[0])
        raise StorageError(f"gsutil du {path}: unparseable output {out!r}")

    def open_read(self, path: str, buffer_size: int | None = None):
        return io.BufferedReader(_GcsRangedReader(self, path),
                                 buffer_size=buffer_size or self.READ_CHUNK)

    #: ranged-read granularity for open_read streams: large enough that a
    #: sequential block scan costs one subprocess per few MB, small enough
    #: that a header probe doesn't pull the whole object
    READ_CHUNK = 4 * 1024 * 1024

    def _invalidate_size(self, *paths: str) -> None:
        """Drop cached stat results for mutated objects — a process that
        overwrites an object and sizes it within the TTL (split math right
        after staging/convert) must see the new size, not the cached one."""
        for p in paths:
            self._size_cache.pop(p, None)

    def write_bytes(self, path: str, data: bytes) -> None:
        self._run("cp", "-", path, input_bytes=data)
        self._invalidate_size(path)

    def open_append(self, path: str):
        return _GcsAppendStream(self, path)

    def move(self, src: str, dst: str) -> None:
        self._run("mv", src, dst)
        self._invalidate_size(src, dst)

    def remove(self, path: str) -> None:
        self._run("rm", path)
        self._invalidate_size(path)

    def put(self, local_path: str, path: str) -> None:
        self._run("cp", local_path, path)
        self._invalidate_size(path)

    def get(self, path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        self._run("cp", path, local_path)

    def put_tree(self, local_dir: str, path: str) -> None:
        # rsync -r preserves relative layout on repeated stagings
        self._run("rsync", "-r", local_dir.rstrip("/"), path.rstrip("/"))
        self._size_cache.clear()    # a prefix-wide write: anything under it

    def get_tree(self, path: str, local_dir: str) -> None:
        os.makedirs(local_dir, exist_ok=True)
        self._run("rsync", "-r", path.rstrip("/"), local_dir.rstrip("/"))


# ---------------------------------------------------------------------------
_registry: dict[str, Storage] = {}
_registry_lock = threading.Lock()


def register_storage(scheme: str, storage: Storage | None) -> None:
    """Override an implementation (tests register tmpdir-backed fakes);
    None clears the override so the default is rebuilt on next use."""
    with _registry_lock:
        if storage is None:
            _registry.pop(scheme, None)
        else:
            _registry[scheme] = storage


def sopen(path: str, buffer_size: int | None = None):
    """Scheme-dispatched binary read stream (the data feed's opener: the
    reference's ``fs.open(inputPath)``, HdfsAvroFileSplitReader.java:201).
    Pass a small ``buffer_size`` for header/magic probes — a
    BufferedReader fills its WHOLE buffer on the first read, so probing
    a remote object with the default scan-sized buffer would fetch MBs
    for a few bytes."""
    return storage_for(path).open_read(path, buffer_size=buffer_size)


def ssize(path: str) -> int:
    """Scheme-dispatched object size (split math over remote listings)."""
    return storage_for(path).size(path)


def storage_for(path: str) -> Storage:
    scheme = scheme_of(path)
    with _registry_lock:
        inst = _registry.get(scheme)
        if inst is None:
            if scheme == "":
                inst = LocalStorage()
            elif scheme == "gs":
                inst = GcsStorage()
            else:
                raise StorageError(
                    f"no storage registered for scheme '{scheme}://' "
                    f"({path})")
            _registry[scheme] = inst
    return inst
