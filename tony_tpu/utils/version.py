"""Build/version metadata injected into every job's frozen config.

Analog of the reference's ``VersionInfo`` (reference: tony-core/src/main/java/
com/linkedin/tony/util/VersionInfo.java:22-142 + gradle/version-info.gradle):
the reference bakes version/revision/branch/user/date into a properties file
at build time and ``TonyClient`` injects them into the job conf so the
history server can show which build ran a job. Here the same fields are
resolved at submission time — from a ``version-info.properties`` file next to
the package if a build produced one, else live from git — and written under
``tony.version.*`` keys into tony-final.xml.
"""

from __future__ import annotations

import getpass
import os
import subprocess
import time
from functools import lru_cache

from tony_tpu import __version__

_UNKNOWN = "Unknown"
_PROPS_FILE = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "version-info.properties")


def _git(*args: str) -> str:
    try:
        out = subprocess.run(
            ["git", *args], capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(_PROPS_FILE))
        return out.stdout.strip() if out.returncode == 0 else _UNKNOWN
    except (OSError, subprocess.TimeoutExpired):
        return _UNKNOWN


def _in_own_checkout() -> bool:
    """True only when the package sits directly in its own git checkout.
    Without this guard a pip-installed copy inside some unrelated repo
    (venv under a monorepo) would stamp jobs with that repo's revision."""
    toplevel = _git("rev-parse", "--show-toplevel")
    return toplevel != _UNKNOWN and \
        os.path.realpath(toplevel) == os.path.realpath(
            os.path.dirname(os.path.dirname(_PROPS_FILE)))


@lru_cache(maxsize=1)
def get_version_info() -> dict[str, str]:
    """version / revision / branch / user / date, baked-file first."""
    info = {
        "version": __version__,
        "revision": _UNKNOWN,
        "branch": _UNKNOWN,
        "user": _UNKNOWN,
        "date": _UNKNOWN,
    }
    if os.path.exists(_PROPS_FILE):
        with open(_PROPS_FILE, encoding="utf-8") as f:
            for line in f:
                k, sep, v = line.strip().partition("=")
                if sep and k in info:
                    info[k] = v
    if _in_own_checkout():
        if info["revision"] == _UNKNOWN:
            info["revision"] = _git("rev-parse", "HEAD")
        if info["branch"] == _UNKNOWN:
            info["branch"] = _git("rev-parse", "--abbrev-ref", "HEAD")
    if info["user"] == _UNKNOWN:
        try:
            info["user"] = getpass.getuser()
        except Exception:
            pass
    if info["date"] == _UNKNOWN:
        info["date"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    return info


def inject_version_info(conf) -> None:
    """Record the build in the job conf (reference: TonyClient ctor
    TonyClient.java:132 calls VersionInfo.injectVersionInfo(conf))."""
    for key, value in get_version_info().items():
        conf.set(f"tony.version.{key}", value)
