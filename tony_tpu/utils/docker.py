"""Docker passthrough for task processes.

The reference enables the YARN docker runtime per job via config
(reference: TonyClient.java:340-349 sets YARN_CONTAINER_RUNTIME_TYPE=docker
+ YARN_CONTAINER_RUNTIME_DOCKER_IMAGE from tony.docker.enabled /
tony.docker.image). Without a YARN runtime to delegate to, the local backend
wraps the executor command in ``docker run`` itself: host networking (the
executor's data-plane/TB/RPC ports must be reachable as registered), the job
dir bind-mounted read-write at the same path (conf, staged sources, and logs
keep their absolute paths), and the container removed on exit.
"""

from __future__ import annotations

import re
import shlex

from tony_tpu.conf import keys as K


def container_name(task_id: str, app_id: str = "app") -> str:
    """Deterministic, docker-safe container name for a task."""
    raw = f"tony-{app_id}-{task_id}"
    return re.sub(r"[^a-zA-Z0-9_.-]", "-", raw)[:128]


def docker_wrap(command: str, conf, job_dir: str,
                env_keys: tuple[str, ...] = (),
                task_id: str = "task", app_id: str = "app") -> str:
    """Wrap ``command`` in `docker run` when tony.docker.enabled is set.

    ``env_keys`` are forwarded from the docker-client process environment
    (bare ``-e KEY``) — the backend sets the task env on that process, so the
    container sees exactly the vars the coordinator assigned the task.

    Kill semantics: backends kill tasks by signalling the process group of
    the docker CLIENT, which does not stop the container (SIGKILL detaches
    the client; the daemon keeps the container running, holding the
    host-network ports). The wrapper therefore names the container
    deterministically and traps TERM/INT to issue ``docker kill`` — the
    backend's SIGTERM-then-SIGKILL escalation reaches the container through
    the trap on the first (TERM) step. A client SIGKILLed before the trap
    fires is the residual gap; ``--rm`` plus the deterministic name lets
    operators sweep strays with ``docker kill $(docker ps -qf name=tony-)``.
    """
    if not conf.get_bool(K.DOCKER_ENABLED_KEY, False):
        return command
    image = conf.get(K.DOCKER_IMAGE_KEY) or ""
    if not image:
        raise ValueError(
            f"{K.DOCKER_ENABLED_KEY} is set but {K.DOCKER_IMAGE_KEY} is not")
    name = container_name(task_id, app_id)
    env_flags = "".join(f"-e {shlex.quote(k)} " for k in env_keys)
    run = (
        f"docker run --rm --name {shlex.quote(name)} --network=host "
        f"{env_flags}"
        f"-v {shlex.quote(job_dir)}:{shlex.quote(job_dir)} "
        f"-w {shlex.quote(job_dir)} "
        f"{shlex.quote(image)} bash -c {shlex.quote(command)}")
    kill = f"docker kill {shlex.quote(name)} >/dev/null 2>&1"
    return (f"trap {shlex.quote(kill)} TERM INT; "
            f"{run} & wait $!")
