"""Process-environment helpers shared by client/coordinator/executor."""

from __future__ import annotations

import os

import tony_tpu


def framework_root() -> str:
    """Directory containing the ``tony_tpu`` package (the repo root when
    running from a checkout)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(tony_tpu.__file__)))


def with_framework_path(env: dict[str, str]) -> dict[str, str]:
    """Ensure child processes can ``import tony_tpu`` regardless of their
    working directory — the analog of the reference shipping its fat jar into
    every container's classpath (ClusterSubmitter.java:57-66)."""
    root = framework_root()
    existing = env.get("PYTHONPATH", "")
    if root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = (root + os.pathsep + existing) if existing else root
    return env
