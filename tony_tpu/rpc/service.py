"""The control-plane service interface.

Python analog of the reference's ``ApplicationRpc`` interface (reference:
tony-core/src/main/java/com/linkedin/tony/rpc/ApplicationRpc.java) — the same
seven methods, implemented by the coordinator and consumed by the client and
the task executors. The ~1300 LoC of protobuf record/PBImpl translation
boilerplate in the reference (rpc/impl/pb/*) collapses into the dataclasses
below plus direct proto construction in server.py/client.py.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass


@dataclass(frozen=True)
class TaskUrl:
    """(name, index, url) record surfaced to the client (reference:
    rpc/TaskUrl.java:11-41)."""
    name: str
    index: str
    url: str


@dataclass(frozen=True)
class ApplicationStatus:
    """Coordinator-served job status (replaces YARN application reports)."""
    status: str = "RUNNING"
    message: str = ""
    session_id: int = 0

    @property
    def finished(self) -> bool:
        return self.status in ("SUCCEEDED", "FAILED", "KILLED")


@dataclass(frozen=True)
class WorkerSpecResponse:
    """Gang-barrier response: empty ``spec`` means "not all registered yet,
    poll again"; once released it carries the cluster spec plus the JAX/TPU
    bootstrap assignment (the TF_CONFIG replacement). ``cluster_epoch``
    identifies the cluster-spec GENERATION: elastic shrink/regrow bumps it
    and re-holds the barrier, so a released payload always carries the
    epoch its spec belongs to. ``channel_spec`` is the coordinator's
    channel-registry entry for THIS worker (JSON: pipeline stage
    id/count + peer hub endpoints; "" for non-pipeline jobs).
    ``incarnation`` is the coordinator process GENERATION (count of
    coordinator starts on this job dir, journal-derived): a restarted
    coordinator serves a higher value, telling re-registering executors
    they are re-attaching, not bootstrapping (0 = not tracked)."""
    spec: str = ""
    coordinator_address: str = ""
    process_id: int = -1
    num_processes: int = 0
    mesh_spec: str = ""
    cluster_epoch: int = 0
    channel_spec: str = ""
    incarnation: int = 0

    @property
    def released(self) -> bool:
        return bool(self.spec)


@dataclass(frozen=True)
class HeartbeatAck:
    """Heartbeat response payload: the job's current GCS token plus the
    coordinator's current cluster-spec epoch. An epoch ahead of the
    executor's own is the elastic resync directive — stop the user
    process at the next safe point and re-run the registration handshake
    (implementations may also return a bare token ``str``; the server
    maps it to epoch 0, the pre-elastic wire shape). ``incarnation`` is
    the coordinator process GENERATION: an incarnation that CHANGES
    mid-job (from a nonzero first-seen value) tells the executor a
    restarted coordinator recovered the session from its journal — it
    re-runs the registration handshake without touching the user
    process (0 = not tracked)."""
    gcs_token: str = ""
    cluster_epoch: int = 0
    incarnation: int = 0


class ApplicationRpc(abc.ABC):
    """Seven-method control-plane protocol (reference proto:
    tensorflow_cluster_service_protos.proto:11-19)."""

    @abc.abstractmethod
    def get_task_urls(self) -> list[TaskUrl]: ...

    @abc.abstractmethod
    def get_cluster_spec(self, task_id: str) -> str: ...

    @abc.abstractmethod
    def register_worker_spec(self, worker: str, spec: str,
                             channel_port: int = 0) -> WorkerSpecResponse:
        """Register the worker's data-plane endpoint (and, for pipeline
        jobs, the listen port of its inter-gang tensor-channel hub — 0
        means the worker runs no channel plane). Implementations may
        keep the pre-channel two-argument signature; the server detects
        it and drops the piggyback rather than TypeError-ing."""
        ...

    @abc.abstractmethod
    def register_tensorboard_url(self, spec: str) -> str: ...

    @abc.abstractmethod
    def register_execution_result(self, exit_code: int, job_name: str,
                                  job_index: str, session_id: str) -> str: ...

    @abc.abstractmethod
    def finish_application(self) -> str: ...

    @abc.abstractmethod
    def task_executor_heartbeat(self, task_id: str, metrics: str = "",
                                spans: str = "", client_time: float = 0.0,
                                client_rtt: float = 0.0,
                                ) -> "HeartbeatAck | str":
        """Record the ping; returns a :class:`HeartbeatAck` carrying the
        job's CURRENT GCS access token ("" when credential scoping is
        off) and the coordinator's cluster-spec epoch — the heartbeat
        doubles as the token-renewal fan-out AND the elastic resync
        channel. Implementations may return a bare token ``str`` (the
        pre-elastic shape); the server maps it to epoch 0.

        ``metrics`` optionally carries a compact JSON snapshot of the
        executor's metrics registry (runtime/metrics.py ``to_wire``),
        piggybacked on the beat — the TaskMonitor/MetricsRpc analog. ""
        (an old-style heartbeat) must always be accepted, and a
        malformed snapshot must never fail the ping: liveness and
        telemetry share the channel but only liveness is load-bearing.

        ``spans`` optionally carries a compact trace-span batch
        (runtime/tracing.py ``encode_batch``: recent spans, plus a
        flight-recorder tail on the final beat after an incident), and
        ``client_time``/``client_rtt`` the sender's wall clock at send
        and its last measured heartbeat RTT — the inputs to the
        coordinator's RTT-midpoint clock-offset estimate
        (``tony_clock_offset_seconds``). All three follow the metrics
        discipline: ""/0 from old-style senders is a plain beat, and a
        malformed span batch is dropped without costing the ping.
        Implementations may keep any older signature (metrics-only or
        task-id-only); the server detects it and drops the piggyback
        rather than TypeError-ing."""
        ...

    def renew_gcs_token(self, token: str) -> None:
        """Replace the job's scoped GCS token (client-pushed renewal;
        impersonation tokens expire ~hourly). Default: ignore — only
        the coordinator holds job credentials."""

    @abc.abstractmethod
    def get_application_status(self) -> ApplicationStatus: ...
