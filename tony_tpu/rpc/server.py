"""gRPC server hosting the control-plane protocol inside the coordinator.

Analog of the reference's ``ApplicationRpcServer`` (reference: tony-core/src/
main/java/com/linkedin/tony/rpc/ApplicationRpcServer.java:1-154): a server
thread inside the coordinator on a port from the 10000-15000 range, fronting an
``ApplicationRpc`` implementation. Hadoop IPC + ProtobufRpcEngine becomes
gRPC; the 14 PBImpl translation classes become the inline request/response
lambdas below. Handlers are registered generically (no codegen plugin needed —
protoc only generates the messages)."""

from __future__ import annotations

import hmac
import logging
import random
import socket
from concurrent import futures

import grpc

from tony_tpu import constants
from tony_tpu.rpc import tony_pb2 as pb
from tony_tpu.rpc.service import ApplicationRpc

log = logging.getLogger(__name__)

SERVICE_NAME = "tony_tpu.ApplicationRpc"


def find_free_port(port_range: tuple[int, int] | None = None) -> int:
    """Pick a free port, preferring the reference's 10000-15000 range
    (ApplicationRpcServer.java:36)."""
    lo, hi = port_range or constants.COORDINATOR_RPC_PORT_RANGE
    for _ in range(64):
        port = random.randint(lo, hi)
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("", port))
                return port
            except OSError:
                continue
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class ApplicationRpcServer:
    """Wraps a grpc.Server around an ApplicationRpc implementation."""

    def __init__(self, impl: ApplicationRpc, port: int | None = None,
                 max_workers: int = 32, secret: str | None = None,
                 tls: tuple[str, str] | None = None) -> None:
        self.impl = impl
        #: per-job shared secret; when set, every call must carry it as
        #: gRPC metadata (the ClientToAMToken + service-ACL analog,
        #: reference: TFPolicyProvider.java:14-26, ApplicationRpcServer
        #: secret-manager wiring :56-70).
        self.secret = secret
        #: (key_path, cert_path) — serve over TLS with the per-job cert
        #: (rpc/tls.py; the HTTPS-keystore analog). Plaintext clients are
        #: rejected at the handshake.
        self.tls = tls
        explicit_port = port is not None
        self.port = port if explicit_port else find_free_port()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.so_reuseport", 0)])
        self._server.add_generic_rpc_handlers((self._make_handler(),))
        if tls is not None:
            from tony_tpu.rpc import tls as _tls
            creds = _tls.server_credentials(*tls)
            bind = lambda p: self._server.add_secure_port(f"[::]:{p}", creds)
        else:
            bind = lambda p: self._server.add_insecure_port(f"[::]:{p}")
        if bind(self.port) == 0:
            if explicit_port:
                # The caller advertised this port; silently moving would
                # strand every client. Fail loudly instead.
                raise OSError(f"could not bind RPC server on requested port {self.port}")
            # Race on our self-chosen port — re-pick and retry once.
            self.port = find_free_port((20000, 30000))
            if bind(self.port) == 0:
                raise OSError("could not bind RPC server port")

    # -- handler table ------------------------------------------------------
    def _make_handler(self) -> grpc.GenericRpcHandler:
        impl = self.impl

        def _get_task_urls(req, ctx):
            return pb.GetTaskUrlsResponse(task_urls=[
                pb.TaskUrlProto(name=u.name, index=u.index, url=u.url)
                for u in impl.get_task_urls()])

        def _get_cluster_spec(req, ctx):
            return pb.GetClusterSpecResponse(
                cluster_spec=impl.get_cluster_spec(req.task_id))

        # Old-signature compatibility (same precedent as the heartbeat
        # metrics piggyback below): a pre-channel impl whose
        # register_worker_spec still takes only (worker, spec) keeps
        # working — the channel-port piggyback is dropped, not fatal.
        try:
            import inspect as _inspect
            _reg_takes_port = len(_inspect.signature(
                impl.register_worker_spec).parameters) >= 3
        except (TypeError, ValueError):
            _reg_takes_port = True

        def _register_worker_spec(req, ctx):
            if _reg_takes_port:
                r = impl.register_worker_spec(req.worker, req.spec,
                                              req.channel_port)
            else:
                r = impl.register_worker_spec(req.worker, req.spec)
            return pb.RegisterWorkerSpecResponse(
                spec=r.spec, coordinator_address=r.coordinator_address,
                process_id=r.process_id, num_processes=r.num_processes,
                mesh_spec=r.mesh_spec,
                cluster_epoch=getattr(r, "cluster_epoch", 0),
                channel_spec=getattr(r, "channel_spec", ""),
                incarnation=getattr(r, "incarnation", 0))

        def _register_tb_url(req, ctx):
            return pb.RegisterTensorBoardUrlResponse(
                spec=impl.register_tensorboard_url(req.spec))

        def _register_result(req, ctx):
            return pb.RegisterExecutionResultResponse(
                message=impl.register_execution_result(
                    req.exit_code, req.job_name, req.job_index, req.session_id))

        def _finish(req, ctx):
            return pb.FinishApplicationResponse(message=impl.finish_application())

        # Old-signature compatibility, both directions: req.metrics /
        # req.spans are "" for old-style SENDERS (proto3 default), and an
        # old-style IMPL whose task_executor_heartbeat takes only task_id
        # (or task_id+metrics, the pre-trace shape) keeps working — the
        # piggyback is dropped rather than TypeError-ing every beat.
        # Decided once at handler build, not per call.
        try:
            import inspect
            _hb_params = inspect.signature(
                impl.task_executor_heartbeat).parameters
            _hb_takes_metrics = len(_hb_params) >= 2
            _hb_takes_trace = "spans" in _hb_params
            _hb_takes_goodput = "goodput" in _hb_params
        except (TypeError, ValueError):
            _hb_takes_metrics = True
            _hb_takes_trace = True
            _hb_takes_goodput = True

        def _heartbeat(req, ctx):
            if _hb_takes_goodput:
                ack = impl.task_executor_heartbeat(
                    req.task_id, req.metrics, spans=req.spans,
                    client_time=req.client_unix_time,
                    client_rtt=req.client_rtt,
                    goodput=getattr(req, "goodput", ""))
            elif _hb_takes_trace:
                ack = impl.task_executor_heartbeat(
                    req.task_id, req.metrics, spans=req.spans,
                    client_time=req.client_unix_time,
                    client_rtt=req.client_rtt)
            elif _hb_takes_metrics:
                ack = impl.task_executor_heartbeat(req.task_id, req.metrics)
            else:
                ack = impl.task_executor_heartbeat(req.task_id)
            # Impls may return a HeartbeatAck (token + cluster epoch) or a
            # bare token string / None (pre-elastic shape → epoch 0).
            if isinstance(ack, str) or ack is None:
                return pb.HeartbeatResponse(gcs_token=ack or "")
            return pb.HeartbeatResponse(gcs_token=ack.gcs_token or "",
                                        cluster_epoch=ack.cluster_epoch,
                                        incarnation=getattr(ack, "incarnation", 0))

        def _renew_gcs_token(req, ctx):
            impl.renew_gcs_token(req.token)
            return pb.RenewGcsTokenResponse()

        def _get_status(req, ctx):
            s = impl.get_application_status()
            return pb.GetApplicationStatusResponse(
                status=s.status, message=s.message, session_id=s.session_id)

        methods = {
            "GetTaskUrls": (_get_task_urls, pb.GetTaskUrlsRequest),
            "GetClusterSpec": (_get_cluster_spec, pb.GetClusterSpecRequest),
            "RegisterWorkerSpec": (_register_worker_spec, pb.RegisterWorkerSpecRequest),
            "RegisterTensorBoardUrl": (_register_tb_url, pb.RegisterTensorBoardUrlRequest),
            "RegisterExecutionResult": (_register_result, pb.RegisterExecutionResultRequest),
            "FinishApplication": (_finish, pb.FinishApplicationRequest),
            "TaskExecutorHeartbeat": (_heartbeat, pb.HeartbeatRequest),
            "RenewGcsToken": (_renew_gcs_token, pb.RenewGcsTokenRequest),
            "GetApplicationStatus": (_get_status, pb.GetApplicationStatusRequest),
        }
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                self._authenticated(fn), request_deserializer=req_cls.FromString,
                response_serializer=lambda msg: msg.SerializeToString())
            for name, (fn, req_cls) in methods.items()
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)

    def _authenticated(self, fn):
        """Require the per-job secret as gRPC metadata when auth is on."""
        if not self.secret:
            return fn
        expected = self.secret

        def checked(req, ctx):
            presented = dict(ctx.invocation_metadata()).get(
                constants.AUTH_METADATA_KEY, "")
            if not hmac.compare_digest(presented, expected):
                ctx.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "missing or invalid tony auth token")
            return fn(req, ctx)

        return checked

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        self._server.start()
        log.info("ApplicationRpcServer listening on port %d", self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)
