"""Per-job TLS for the control plane (and the history server's HTTPS).

The reference ships transport security as HTTPS keystore config for its
history server (reference: tony-core/src/main/java/com/linkedin/tony/
TonyConfigurationKeys.java:55-68) and Hadoop-managed kerberos/token auth on
the IPC plane (TonyClient.java:509 delegation tokens). The TPU-native
equivalent has no Hadoop security substrate, so the framework carries its
own: a per-job self-signed certificate generated at submission, staged next
to ``.tony-secret`` (same chmod-600 discipline, backend/tpu.py), with

  * the coordinator's gRPC server on TLS (``ssl_server_credentials``),
  * every client channel pinned to exactly that certificate
    (``root_certificates=`` the job cert — a private per-job CA of one),
  * hostname checks satisfied by a fixed target-name override: the
    coordinator's real hostname is unknowable at submission (any VM/slice
    host), so the cert names ``tony-coordinator`` and clients set
    ``grpc.ssl_target_name_override`` — pinning to the per-job cert is what
    authenticates, not a public-CA hostname chain.

Key material never crosses the network in the clear: the key/cert files
travel over scp like the secret, and the shared-secret auth metadata now
rides inside the encrypted channel.
"""

from __future__ import annotations

import datetime
import os

from tony_tpu import constants

#: CN/SAN on every per-job cert; clients override the gRPC target name to
#: this, because the coordinator's hostname is unknown at cert time.
TLS_TARGET_NAME = "tony-coordinator"


def generate_self_signed(out_dir: str, days: int = 397) -> tuple[str, str]:
    """Generate a per-job EC key + self-signed cert into ``out_dir``.

    Returns (key_path, cert_path). The key file is 0600 (same discipline
    as ``.tony-secret``); the cert is public. Requires the ``cryptography``
    package (present in the baked image); raises a clear error otherwise.

    The default validity (397 days, the public-CA maximum) deliberately
    outlives any plausible job: the cert is per-job and pinned, so a
    short lifetime buys nothing — but an expiry DURING a long run would
    brick relaunch channels (AM-crash recovery, late ``tony kill``)."""
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError as e:     # pragma: no cover - baked image has it
        raise RuntimeError(
            "tony.tls.enabled requires the 'cryptography' package to "
            "generate the per-job certificate") from e

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, TLS_TARGET_NAME)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName(TLS_TARGET_NAME)]), critical=False)
            .sign(key, hashes.SHA256()))

    key_path = os.path.join(out_dir, constants.TONY_TLS_KEY_FILE)
    cert_path = os.path.join(out_dir, constants.TONY_TLS_CERT_FILE)
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return key_path, cert_path


def server_credentials(key_path: str, cert_path: str):
    """gRPC server credentials from the per-job key/cert files."""
    import grpc
    with open(key_path, "rb") as f:
        key = f.read()
    with open(cert_path, "rb") as f:
        cert = f.read()
    return grpc.ssl_server_credentials([(key, cert)])


def channel_credentials(cert_path: str):
    """(credentials, channel options) pinning a client channel to the
    per-job cert. The options set the target-name override that makes the
    fixed-CN cert verify against any coordinator address."""
    import grpc
    with open(cert_path, "rb") as f:
        cert = f.read()
    return (grpc.ssl_channel_credentials(root_certificates=cert),
            (("grpc.ssl_target_name_override", TLS_TARGET_NAME),))


def env_cert_path() -> str | None:
    """The staged cert path from the launch environment (executors and
    in-job clients), or None when TLS is off for this job."""
    return os.environ.get(constants.TONY_TLS_CERT) or None
