"""Retrying control-plane RPC client, shared by the job client and executors.

Analog of the reference's singleton retry-proxy ``ApplicationRpcClient``
(reference: tony-core/src/main/java/com/linkedin/tony/rpc/impl/
ApplicationRpcClient.java:48-162): one instance per coordinator address, every
call wrapped in retry-with-backoff so executor startup races against
coordinator startup resolve themselves (the reference uses Hadoop
RetryProxy with exponential backoff, :80-92)."""

from __future__ import annotations

import logging
import os
import random
import threading
import time

import grpc

from tony_tpu import constants
from tony_tpu.rpc import tony_pb2 as pb
from tony_tpu.rpc.server import SERVICE_NAME
from tony_tpu.rpc.service import (ApplicationRpc, ApplicationStatus,
                                  HeartbeatAck, TaskUrl, WorkerSpecResponse)

log = logging.getLogger(__name__)

_instances: dict[str, "ApplicationRpcClient"] = {}
_instances_lock = threading.Lock()


class RpcRetryError(RuntimeError):
    """Raised when a call keeps failing past the retry budget."""


class ApplicationRpcClient(ApplicationRpc):
    """gRPC client with retry/backoff implementing ApplicationRpc."""

    def __init__(self, address: str, max_retries: int = 30,
                 base_backoff_s: float = 0.1, max_backoff_s: float = 5.0,
                 secret: str | None = None,
                 tls_cert: str | None = None) -> None:
        self.address = address
        # Per-job auth token (ClientToAMToken analog). Defaults from the
        # TONY_SECRET env var so executors — which receive the secret in
        # their launch environment — authenticate without plumbing.
        if secret is None:
            secret = os.environ.get(constants.TONY_SECRET) or None
        self._metadata = ((constants.AUTH_METADATA_KEY, secret),) if secret \
            else None
        self.max_retries = max_retries
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        # Per-job TLS (rpc/tls.py): pin the channel to the staged job cert.
        # Defaults from TONY_TLS_CERT (a path) so executors pick it up from
        # their launch environment exactly like the secret.
        from tony_tpu.rpc import tls as _tls
        if tls_cert is None:
            tls_cert = _tls.env_cert_path()
        if tls_cert:
            creds, options = _tls.channel_credentials(tls_cert)
            self._channel = grpc.secure_channel(address, creds,
                                                options=options)
        else:
            self._channel = grpc.insecure_channel(address)
        m = f"/{SERVICE_NAME}/"
        self._get_task_urls = self._channel.unary_unary(
            m + "GetTaskUrls",
            request_serializer=pb.GetTaskUrlsRequest.SerializeToString,
            response_deserializer=pb.GetTaskUrlsResponse.FromString)
        self._get_cluster_spec = self._channel.unary_unary(
            m + "GetClusterSpec",
            request_serializer=pb.GetClusterSpecRequest.SerializeToString,
            response_deserializer=pb.GetClusterSpecResponse.FromString)
        self._register_worker_spec = self._channel.unary_unary(
            m + "RegisterWorkerSpec",
            request_serializer=pb.RegisterWorkerSpecRequest.SerializeToString,
            response_deserializer=pb.RegisterWorkerSpecResponse.FromString)
        self._register_tb_url = self._channel.unary_unary(
            m + "RegisterTensorBoardUrl",
            request_serializer=pb.RegisterTensorBoardUrlRequest.SerializeToString,
            response_deserializer=pb.RegisterTensorBoardUrlResponse.FromString)
        self._register_result = self._channel.unary_unary(
            m + "RegisterExecutionResult",
            request_serializer=pb.RegisterExecutionResultRequest.SerializeToString,
            response_deserializer=pb.RegisterExecutionResultResponse.FromString)
        self._finish = self._channel.unary_unary(
            m + "FinishApplication",
            request_serializer=pb.FinishApplicationRequest.SerializeToString,
            response_deserializer=pb.FinishApplicationResponse.FromString)
        self._heartbeat = self._channel.unary_unary(
            m + "TaskExecutorHeartbeat",
            request_serializer=pb.HeartbeatRequest.SerializeToString,
            response_deserializer=pb.HeartbeatResponse.FromString)
        self._renew_gcs_token = self._channel.unary_unary(
            m + "RenewGcsToken",
            request_serializer=pb.RenewGcsTokenRequest.SerializeToString,
            response_deserializer=pb.RenewGcsTokenResponse.FromString)
        self._get_status = self._channel.unary_unary(
            m + "GetApplicationStatus",
            request_serializer=pb.GetApplicationStatusRequest.SerializeToString,
            response_deserializer=pb.GetApplicationStatusResponse.FromString)

    @classmethod
    def get_instance(cls, address: str) -> "ApplicationRpcClient":
        """Singleton per address (reference: ApplicationRpcClient.getInstance:
        48-55)."""
        with _instances_lock:
            if address not in _instances:
                _instances[address] = cls(address)
            return _instances[address]

    @classmethod
    def reconnect(cls, address: str) -> "ApplicationRpcClient":
        """Evict any cached client for ``address`` and dial a fresh
        channel. A coordinator that died and came back on the SAME
        address (the journal-recovery restart rebinds its old port)
        leaves the cached channel deep in gRPC's connection backoff —
        calls keep failing fast long after the server is serving again.
        A new channel dials immediately."""
        with _instances_lock:
            old = _instances.pop(address, None)
        if old is not None:
            try:
                old._channel.close()
            except Exception:
                pass
        return cls.get_instance(address)

    def close(self) -> None:
        self._channel.close()
        with _instances_lock:
            # Only evict the registry entry if it is THIS client — a
            # directly-constructed client must not break the singleton.
            if _instances.get(self.address) is self:
                del _instances[self.address]

    # -- retry wrapper ------------------------------------------------------
    def _call(self, stub, request, retries: int | None = None,
              idempotent: bool = True, deadline_s: float = 10.0):
        """Retry policy: UNAVAILABLE always retries (the request never reached
        a serving coordinator). DEADLINE_EXCEEDED may mean the server *did*
        process the call, so it only retries for idempotent methods — the
        coordinator's register_worker_spec/heartbeat are idempotent by
        contract (keyed on task id); register_execution_result is not.

        ``deadline_s`` is the per-ATTEMPT gRPC deadline; idempotent reads
        on hot paths (get_cluster_spec during the barrier poll,
        get_application_status from the client's monitor loop) pass a
        tighter one so a wedged coordinator surfaces as a quick retryable
        DEADLINE_EXCEEDED instead of a 10s stall per attempt.

        The backoff sleep is jittered (uniform in [0.5, 1.0] of the
        nominal delay): a coordinator restart makes every executor's
        calls fail at the same instant, and unjittered exponential
        backoff would re-synchronize them into thundering-herd retry
        waves against the recovering process.

        ``request`` may be a zero-arg callable, rebuilt PER ATTEMPT —
        for requests carrying a send timestamp (the heartbeat's
        clock-offset stamp), where resending stale bytes after a 10s
        deadline + backoff would corrupt the estimate by that delay."""
        retries = self.max_retries if retries is None else retries
        backoff = self.base_backoff_s
        last_err: Exception | None = None
        for _ in range(retries):
            try:
                req = request() if callable(request) else request
                return stub(req, timeout=deadline_s, metadata=self._metadata)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                retryable = code == grpc.StatusCode.UNAVAILABLE or (
                    idempotent and code == grpc.StatusCode.DEADLINE_EXCEEDED)
                if not retryable:
                    raise
                last_err = e
                time.sleep(backoff * (0.5 + random.random() / 2))
                backoff = min(backoff * 2, self.max_backoff_s)
        raise RpcRetryError(
            f"RPC to {self.address} failed after {retries} retries: {last_err}")

    # -- the seven methods --------------------------------------------------
    def get_task_urls(self) -> list[TaskUrl]:
        resp = self._call(self._get_task_urls, pb.GetTaskUrlsRequest())
        return [TaskUrl(u.name, u.index, u.url) for u in resp.task_urls]

    def get_cluster_spec(self, task_id: str) -> str:
        # Idempotent barrier-poll read: tight per-attempt deadline so a
        # wedged (or restarting) coordinator costs 3s per attempt, not 10.
        resp = self._call(self._get_cluster_spec,
                          pb.GetClusterSpecRequest(task_id=task_id),
                          deadline_s=3.0)
        return resp.cluster_spec

    def register_worker_spec(self, worker: str, spec: str,
                             channel_port: int = 0) -> WorkerSpecResponse:
        resp = self._call(self._register_worker_spec,
                          pb.RegisterWorkerSpecRequest(
                              worker=worker, spec=spec,
                              channel_port=channel_port))
        return WorkerSpecResponse(
            spec=resp.spec, coordinator_address=resp.coordinator_address,
            process_id=resp.process_id, num_processes=resp.num_processes,
            mesh_spec=resp.mesh_spec, cluster_epoch=resp.cluster_epoch,
            channel_spec=resp.channel_spec,
            incarnation=getattr(resp, "incarnation", 0))

    def register_tensorboard_url(self, spec: str) -> str:
        resp = self._call(self._register_tb_url,
                          pb.RegisterTensorBoardUrlRequest(spec=spec))
        return resp.spec

    def register_execution_result(self, exit_code: int, job_name: str,
                                  job_index: str, session_id: str) -> str:
        resp = self._call(self._register_result,
                          pb.RegisterExecutionResultRequest(
                              exit_code=exit_code, job_name=job_name,
                              job_index=job_index, session_id=session_id),
                          idempotent=False)
        return resp.message

    def finish_application(self, retries: int | None = None) -> str:
        resp = self._call(self._finish, pb.FinishApplicationRequest(),
                          retries=retries)
        return resp.message

    def task_executor_heartbeat(self, task_id: str, metrics: str = "",
                                spans: str = "", client_time: float = 0.0,
                                client_rtt: float = 0.0,
                                goodput: str = "") -> HeartbeatAck:
        # Heartbeats get a tight retry budget: the executor-side heartbeater
        # counts consecutive failures itself (reference: TaskExecutor.java:
        # 264-268 dies after 5 failed sends). Returns the job's current
        # GCS token ("" when scoping is off) — the renewal fan-out — plus
        # the coordinator's cluster-spec epoch (the elastic resync signal;
        # an old-wire response leaves it at the proto3 default 0).
        # ``metrics``: optional piggybacked registry snapshot (compact
        # JSON); "" keeps the old-style liveness-only beat. ``spans``:
        # optional trace-span batch (tracing.encode_batch). The request
        # stamps the sender's wall clock at send unless the caller passed
        # one explicitly (client_time=0 means "stamp now"; pass a
        # negative value to suppress the stamp entirely) — with
        # ``client_rtt`` (the caller's last measured beat RTT) it feeds
        # the coordinator's RTT-midpoint clock-offset estimate.
        # ``goodput``: optional cumulative goodput-ledger snapshot
        # (runtime/goodput.py wire JSON); "" means no ledger.
        def build():
            # stamped per ATTEMPT: a retried beat must carry the retry's
            # send time, not bytes stamped before a 10s deadline expiry
            now = time.time() if client_time == 0.0 \
                else (0.0 if client_time < 0 else client_time)
            return pb.HeartbeatRequest(task_id=task_id,
                                       metrics=metrics or "",
                                       spans=spans or "",
                                       client_unix_time=now,
                                       client_rtt=max(0.0, client_rtt),
                                       goodput=goodput or "")

        resp = self._call(self._heartbeat, build, retries=2)
        return HeartbeatAck(gcs_token=resp.gcs_token,
                            cluster_epoch=resp.cluster_epoch,
                            incarnation=getattr(resp, "incarnation", 0))

    def renew_gcs_token(self, token: str) -> None:
        self._call(self._renew_gcs_token,
                   pb.RenewGcsTokenRequest(token=token))

    def get_application_status(self) -> ApplicationStatus:
        # Idempotent status poll (client monitor loop, ~every few seconds):
        # same tight-deadline treatment as get_cluster_spec.
        resp = self._call(self._get_status, pb.GetApplicationStatusRequest(),
                          deadline_s=3.0)
        return ApplicationStatus(status=resp.status, message=resp.message,
                                 session_id=resp.session_id)
