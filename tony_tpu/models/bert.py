"""BERT-base encoder + MLM head, for the 16-worker multi-host config.

BASELINE.json's final progression step is "BERT-base pretraining (16
workers, jax.distributed multi-host)". Reuses the framework's TPU-first
blocks — flash/dense attention (bidirectional), fused-norm math, logical-
axis sharding — with the classic BERT shape: learned position embeddings,
post-LN transformer encoder, GELU MLP, weight-tied MLM head.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tony_tpu.ops.attention import flash_attention, reference_attention
from tony_tpu.ops.norms import layer_norm_reference
from tony_tpu.parallel.sharding import DEFAULT_RULES, constrain
from tony_tpu.models.train import masked_cross_entropy


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab: int = 2
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
                       d_ff=512, max_seq=128)


def init_params(rng: jax.Array, cfg: BertConfig) -> dict:
    d, h, hd, f, L = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                      cfg.n_layers)
    dt = cfg.dtype
    ks = iter(jax.random.split(rng, 16))

    def dense(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    return {
        "tok_embed": dense((cfg.vocab_size, d), d),
        "pos_embed": dense((cfg.max_seq, d), d),
        "type_embed": dense((cfg.type_vocab, d), d),
        "embed_ln": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "blocks": {
            "wq": dense((L, d, h, hd), d),
            "wk": dense((L, d, h, hd), d),
            "wv": dense((L, d, h, hd), d),
            "wo": dense((L, h, hd, d), d),
            "attn_ln": {"scale": jnp.ones((L, d), dt),
                        "bias": jnp.zeros((L, d), dt)},
            "w_in": dense((L, d, f), d),
            "b_in": jnp.zeros((L, f), dt),
            "w_out": dense((L, f, d), f),
            "b_out": jnp.zeros((L, d), dt),
            "mlp_ln": {"scale": jnp.ones((L, d), dt),
                       "bias": jnp.zeros((L, d), dt)},
        },
        "mlm_dense": dense((d, d), d),
        "mlm_ln": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "mlm_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
    }


def logical_axes(cfg: BertConfig) -> dict:
    ln = lambda lead: {"scale": lead + ("norm",), "bias": lead + ("norm",)}
    return {
        "tok_embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "type_embed": (None, "embed"),
        "embed_ln": ln(()),
        "blocks": {
            "wq": ("stage", "embed", "heads", "kv"),
            "wk": ("stage", "embed", "heads", "kv"),
            "wv": ("stage", "embed", "heads", "kv"),
            "wo": ("stage", "heads", "kv", "embed"),
            "attn_ln": ln(("stage",)),
            "w_in": ("stage", "embed", "mlp"),
            "b_in": ("stage", "mlp"),
            "w_out": ("stage", "mlp", "embed"),
            "b_out": ("stage", "embed"),
            "mlp_ln": ln(("stage",)),
        },
        "mlm_dense": ("embed", "embed"),
        "mlm_ln": ln(()),
        "mlm_bias": ("vocab",),
    }


def _attention(q, k, v):
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, causal=False)
    return reference_attention(q, k, v, causal=False)


def _block(x, p, cfg: BertConfig, mesh, rules):
    h = constrain(x, ("batch", "seq", "embed"), mesh, rules)
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"])
    o = _attention(q, k, v)
    attn = jnp.einsum("bshk,hkd->bsd", o, p["wo"])
    x = layer_norm_reference(x + attn, p["attn_ln"]["scale"],
                             p["attn_ln"]["bias"])   # post-LN (original BERT)
    inner = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, p["w_in"]) + p["b_in"])
    inner = constrain(inner, ("batch", "seq", "mlp"), mesh, rules)
    mlp = jnp.einsum("bsf,fd->bsd", inner, p["w_out"]) + p["b_out"]
    return layer_norm_reference(x + mlp, p["mlp_ln"]["scale"],
                                p["mlp_ln"]["bias"])


def forward(params: dict, tokens: jax.Array, cfg: BertConfig,
            type_ids: jax.Array | None = None,
            mesh: Mesh | None = None, rules=DEFAULT_RULES) -> jax.Array:
    """tokens [B, S] → MLM logits [B, S, V] (f32)."""
    b, s = tokens.shape
    x = params["tok_embed"][tokens]
    x = x + params["pos_embed"][None, :s]
    if type_ids is not None:
        x = x + params["type_embed"][type_ids]
    x = layer_norm_reference(x, params["embed_ln"]["scale"],
                             params["embed_ln"]["bias"]).astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"), mesh, rules)

    def body(x, layer_params):
        return _block(x, layer_params, cfg, mesh, rules), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    h = jax.nn.gelu(jnp.einsum("bsd,de->bse", x, params["mlm_dense"]))
    h = layer_norm_reference(h, params["mlm_ln"]["scale"],
                             params["mlm_ln"]["bias"])
    # weight-tied output projection
    logits = jnp.einsum("bsd,vd->bsv", h, params["tok_embed"],
                        preferred_element_type=jnp.float32)
    return logits + params["mlm_bias"]


def mlm_loss(params: dict, batch: dict, cfg: BertConfig,
             mesh: Mesh | None = None, rules=DEFAULT_RULES) -> jax.Array:
    """batch: {"tokens" [B,S], "targets" [B,S] (-1 = unmasked/ignore)}."""
    logits = forward(params, batch["tokens"], cfg,
                     batch.get("type_ids"), mesh, rules)
    return masked_cross_entropy(logits, batch["targets"])
