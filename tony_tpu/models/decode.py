"""Autoregressive decoding with a KV cache for the flagship transformer.

The inference half of the model stack (the reference delegates all compute,
so this — like training — is green-field per SURVEY.md §2.3). TPU-first
choices:

- **Static shapes everywhere**: the cache is a fixed [L, B, max_len, KV, D]
  buffer (KV = cfg.kv_heads — n_heads/n_kv_heads× smaller under
  grouped-query attention) updated with ``lax.dynamic_update_slice``; the
  decode loop is a ``lax.scan`` over step index — one compiled program
  regardless of prompt or generation length.
- **Prefill/decode split**: the prompt is processed in one batched forward
  (MXU-friendly big matmuls, flash attention) that also fills the cache;
  each generated token then runs the cheap single-position path attending
  over the cache.
- **Masked cache attention**: positions beyond the current length are
  masked with -inf rather than sliced (dynamic slices of data-dependent
  length would break XLA's static shapes).

Sharding: tensor-parallel decode works by XLA sharding propagation — pass
params sharded by the model's logical axes (shard_pytree + logical_axes)
and call under ``jax.set_mesh``; outputs are token-identical to unsharded
decode (test-verified on a tp×dp mesh). The module adds no explicit
sharding constraints of its own; the cache layout follows the q/k/v
projections' propagated shardings.

Usage::

    out = generate(params, prompt_tokens, cfg, max_new_tokens=64,
                   rng=jax.random.PRNGKey(0), temperature=0.8)
    out.tokens      # [B, prompt_len + max_new_tokens]
"""

from __future__ import annotations

import functools
import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tony_tpu.models import transformer as T
from tony_tpu.models.quantize import QuantizedWeight
from tony_tpu.ops.norms import rms_norm_reference
from tony_tpu.parallel.moe import moe_ffn


#: TOKEN POSITIONS (the sequence axis, NOT batch x seq) STRICTLY ABOVE
#: which a quantized matmul is "prefill-shaped": compute-bound, not
#: weight-read-bound, so the int8 weight converts to the bf16 compute
#: dtype ONCE per call (the materialized copy amortizes over the many
#: activation rows) and the dot runs at bf16 MXU throughput instead of
#: f32. Gating on the sequence axis alone keeps every decode-shaped
#: call — single steps (S=1) and speculative verify chunks (S=k+1) — on
#: the fused-f32 kernel AT ANY BATCH SIZE: a batch-widened decode step
#: must never flip kernels (re-paying the materialized-copy cost per
#: step), and verify vs single-step logits must come from the SAME
#: kernel or the chunked-verify == single-step token-identity contract
#: quietly erodes on TPU.
#:
#: The threshold sits ON a power-of-two admission-ladder rung and the
#: comparison is STRICT (> not >=) on purpose: ``next_pow2(n) <= 256
#: iff n <= 256``, so a prompt and its padded power-of-two bucket always
#: land on the same side — bucketed admission (serve.py) and an
#: exact-length prefill of the same prompt pick the SAME kernel, keeping
#: quantized serving == solo generate on TPU. (A custom
#: ``admission_buckets`` ladder whose rungs straddle 256 — e.g. a
#: 300-token bucket holding 200-token prompts — reintroduces the flip;
#: keep a rung at 256 if you serve int8 weights in bf16.)
#:
#: Known carve-out: SHARED-PREFIX serving decomposes one logical prompt
#: into a template prefill (P positions) and a suffix extend (S
#: positions), each gated on its own length, while the solo baseline
#: prefills P+S in one call — when those land on different sides of the
#: rung (e.g. P, S <= 256 < P+S), the components run different kernels
#: and near-tie argmaxes can flip vs the monolithic prefill. This is
#: inherent to any shape-gated kernel choice applied to a decomposed
#: computation, and it is the SAME caveat class as chunked-vs-monolithic
#: matmul noise on TPU (see speculative_generate's caveats): quantized
#: shared-prefix exactness is CPU-pinned; on TPU it holds modulo
#: near-tie flips.
_QUANT_PREFILL_MIN_S = 256


def _weinsum(spec, x, w, pet=None):
    """Weight-matmul dispatch: plain arrays take the ordinary einsum;
    :class:`~tony_tpu.models.quantize.QuantizedWeight` operands compute
    the dot on the int8 weight cast to FLOAT32 (not the bf16 compute
    dtype: XLA fuses the int8→f32 convert into the dot's operand read,
    while int8→bf16 MATERIALIZES a full-size converted copy — measured
    3× slower on the lm_head matmul; f32 is exact for integers ≤ 127
    anyway) and apply the per-output-channel scale OUTSIDE the
    contraction. ``pet=jnp.float32`` callers (the lm_head) get f32 out
    either way.

    PREFILL-shaped quantized matmuls (more than ``_QUANT_PREFILL_MIN_S``
    token positions on the sequence axis, bf16 activations) instead cast
    the int8 weight to bf16: prefill over a long prompt is compute-bound
    and f32 MXU throughput is far below bf16, so the one-time converted
    copy is the right trade there — the scale still applies outside the
    contraction with f32 accumulation, so the numerics contract (int8
    values exact in the operand dtype, scale exact in f32) is unchanged.
    Decode-shaped calls (any batch size — the gate reads the sequence
    axis only), 2-D projections (the lm_head's last-position read), and
    f32 activations (the CPU/test path) keep the f32 route bit-for-bit;
    the strict on-a-ladder-rung threshold keeps bucket-padded and
    exact-length prefills of the same prompt on the same kernel (see the
    constant's comment)."""
    if isinstance(w, QuantizedWeight):
        s_len = x.shape[1] if x.ndim >= 3 else 1
        if s_len > _QUANT_PREFILL_MIN_S and x.dtype == jnp.bfloat16:
            y = jnp.einsum(spec, x, w.q.astype(x.dtype),
                           preferred_element_type=jnp.float32) * w.scale
            return y if pet == jnp.float32 else y.astype(x.dtype)
        y = jnp.einsum(spec, x.astype(jnp.float32),
                       w.q.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * w.scale
        return y if pet == jnp.float32 else y.astype(x.dtype)
    return jnp.einsum(spec, x, w, preferred_element_type=pet)


class GenerateOutput(NamedTuple):
    tokens: jax.Array        # [B, prompt_len + max_new_tokens]
    logprobs: jax.Array      # [B, max_new_tokens] logprob of each sampled token


def _ring_capacity(cfg: T.TransformerConfig) -> int:
    """Rolling-cache rows per slot (0 = linear cache of max_len rows)."""
    return cfg.kv_cache_capacity


def init_kv_cache(cfg: T.TransformerConfig, batch: int,
                  max_len: int) -> dict:
    """Zeroed cache pytree: k/v of shape [L, B, max_len, KV, hd] — KV is
    cfg.kv_heads, so grouped-query configs carry an n_heads/n_kv_heads×
    smaller cache (the main GQA payoff at long max_len).

    ``cfg.kv_cache_dtype == "int8"`` stores k/v as int8 with per-token,
    per-kv-head absmax scales in parallel ``k_scale``/``v_scale`` buffers
    of shape [L, B, max_len, KV, 1] (f32) — the SAME rank and leading
    dims as k/v, so every cache write path (contiguous slice, bounded
    window, per-row scatter) applies to the scale buffers unchanged with
    a trailing dim of 1. Cache memory and read traffic halve vs bf16
    (each of k and v costs 1 + 4/hd bytes per element ≈ 1.06 at hd=64,
    vs 2 bf16); see :func:`_kv_quantize` for the numerics.

    ``cfg.kv_cache_capacity`` allocates a ROLLING cache of that many
    rows instead of ``max_len`` — writes wrap modulo the capacity
    (sliding-window models only; the ring read masks by each row's
    absolute position). Memory is O(capacity) however long the stream
    runs."""
    cap = _ring_capacity(cfg)
    rows = cap or max_len
    if cap and cfg.attn_window and cap >= 4 * cfg.attn_window:
        # _ring_cached_attention is dense over ALL capacity rows every
        # step — per-token cost is O(capacity), NOT O(window). Capacity
        # near the window is the intended regime; a large multiple
        # silently forfeits the sliding window's cost bound.
        warnings.warn(
            f"kv_cache_capacity={rows} is {rows // cfg.attn_window}x "
            f"attn_window={cfg.attn_window}: ring-cache attention reads "
            "every capacity row per token (O(capacity), not O(window)) — "
            "size the capacity near the window", stacklevel=2)
    shape = (cfg.n_layers, batch, rows, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_quant:
        sshape = shape[:-1] + (1,)
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.float32),
                "v_scale": jnp.zeros(sshape, jnp.float32),
                "length": jnp.zeros((), jnp.int32)}
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "length": jnp.zeros((), jnp.int32)}


#: cache keys that hold per-position buffers (and so follow every write/
#: gather/tile path together); "length" is the only non-buffer key
_KV_BUFS = ("k", "v", "k_scale", "v_scale")


def _kv_bufs(cache: dict) -> dict:
    """The cache's position-indexed buffers (k/v + scales when present),
    without the length field."""
    return {n: cache[n] for n in _KV_BUFS if n in cache}


def _kv_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization of a K/V chunk [..., KV, hd] along its
    head dim: scale = absmax/127 per (token, kv-head), q = round(x/scale)
    in [-127, 127]. Returns (q int8, scale [..., KV, 1] f32). Integer
    values up to 127 are exact in bf16, so the dequantized dot can cast
    the int8 operand straight to the compute dtype and apply the scale
    OUTSIDE the contraction (it is constant along hd — see
    :func:`_cached_attention`), keeping HBM reads int8-wide."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


# Length-aware decode attention: caches at or above this many positions
# take the block-wise path whose cost scales with the LIVE length
# (ceil(length/block) blocks) instead of the padded max_len. Below it the
# dense einsum is both cheaper (no while_loop overhead) and bit-exact
# against the training forward, which the CPU equivalence tests rely on.
DECODE_BLOCK = 256
_BLOCKWISE_MIN_LEN = 2 * DECODE_BLOCK


def _q_positions(q_start, b, n_q):
    """[B, Q] absolute positions for a decode chunk. ``q_start`` may be a
    scalar (all rows at the same frontier — plain generate) or a [B]
    vector (per-row frontiers — batched speculative decoding, where each
    row commits its own acceptance length)."""
    q_start = jnp.asarray(q_start)
    if q_start.ndim == 0:
        q_start = jnp.broadcast_to(q_start, (b,))
    return q_start[:, None] + jnp.arange(n_q)[None, :]


def _cached_attention_blockwise(q, bufs, li, q_start,
                                block: int = DECODE_BLOCK,
                                attn_window: int | None = None):
    """Online-softmax cached attention reading only the ACTIVE cache
    blocks. The dense path reads all max_len rows every step — cost
    scales with the padded buffer, not the tokens generated, which at
    serving max_len (2k-32k) dominates decode wall-clock. Here a
    ``fori_loop`` with a traced trip count ``ceil((q_start+K)/block)``
    walks only blocks that can hold unmasked positions, carrying the
    standard (running max, normalizer, weighted-value) flash state; the
    compiled program is static-shape (one [block]-row slice per step)
    while the executed cost follows the live length.

    Takes the STACKED caches [L, B, max_len, KV, hd] plus this layer's
    static index ``li`` and slices each block 5-D directly — slicing the
    layer first (``k_all[li]``) reads loop-invariant in the fori_loop, so
    XLA hoists and MATERIALIZES the full padded per-layer cache before
    the loop, re-paying exactly the O(max_len) traffic this path exists
    to avoid (measured: 5x decode slowdown at max_len 8192).

    Same contract as the dense path: q [B, K, H, hd] at positions
    q_start..q_start+K-1 (GQA reads its shared K/V head unexpanded),
    query i attends positions <= q_start+i. Cache-dtype operands with f32
    accumulation. Numerics are flash-style (running max/rescale) rather
    than one global softmax, so logits agree with the dense path to
    normal flash tolerance, not bitwise.

    Trailing partial blocks: ``max_len`` need not divide by ``block`` —
    the last slice start is clamped (dynamic_slice semantics) and a
    position-range mask discards the re-read rows.

    Quantized caches (``k_scale``/``v_scale`` present in ``bufs``): the
    int8 K/V blocks cast to the compute dtype inside the dots (integer
    values <= 127 are exact in bf16) and the per-token scales apply
    OUTSIDE the hd-contractions they are constant along — the K scale on
    the [.., q, s] scores, the V scale folded into ``p`` — so HBM block
    reads stay int8-wide."""
    k_all, v_all = bufs["k"], bufs["v"]
    quant = "k_scale" in bufs
    b, n_q, h, d = q.shape
    max_len = k_all.shape[2]
    kv = k_all.shape[3]
    group = h // kv
    scale = d ** -0.5
    q_pos = _q_positions(q_start, b, n_q)                       # [B, Q]
    qg = q.reshape(b, n_q, kv, group, d)
    n_active = (jnp.max(q_pos) + block) // block                # traced
    # sliding window: blocks entirely older than every row's window are
    # never read — the loop STARTS at the window's first block, so
    # per-token serving cost is O(window) regardless of history length
    lo = (jnp.maximum(jnp.min(q_pos) - attn_window + 1, 0) // block
          if attn_window is not None else 0)

    m0 = jnp.full((b, kv, group, n_q), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, kv, group, n_q), jnp.float32)
    acc0 = jnp.zeros((b, kv, group, n_q, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        start = jnp.minimum(i * block, max_len - block)
        kb = jax.lax.dynamic_slice(
            k_all, (li, 0, start, 0, 0), (1, b, block, kv, d))[0]
        vb = jax.lax.dynamic_slice(
            v_all, (li, 0, start, 0, 0), (1, b, block, kv, d))[0]
        if quant:
            kb, vb = kb.astype(q.dtype), vb.astype(q.dtype)
        k_pos = start + jnp.arange(block)                       # [S]
        # >= i*block drops rows re-read by a clamped trailing slice
        mask = ((k_pos[None, None, :] >= i * block)
                & (k_pos[None, None, :] <= q_pos[:, :, None]))  # [B, Q, S]
        if attn_window is not None:
            mask = mask & (q_pos[:, :, None] - k_pos[None, None, :]
                           < attn_window)
        s = jnp.einsum("bqkgd,bskd->bkgqs", qg, kb,
                       preferred_element_type=jnp.float32) * scale
        if quant:
            ksb = jax.lax.dynamic_slice(
                bufs["k_scale"], (li, 0, start, 0, 0),
                (1, b, block, kv, 1))[0, ..., 0]                # [B, S, KV]
            s = s * ksb.transpose(0, 2, 1)[:, :, None, None, :]
        s = jnp.where(mask[:, None, None], s, -jnp.inf)
        new_m = jnp.maximum(m, s.max(axis=-1))
        # all-masked (query, block) pairs keep m=-inf; subtract 0 there so
        # exp(-inf - 0) = 0 instead of exp(nan)
        safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
        alpha = jnp.exp(m - safe_m)                             # -inf -> 0
        p = jnp.exp(s - safe_m[..., None])
        l = l * alpha + p.sum(axis=-1)
        if quant:
            vsb = jax.lax.dynamic_slice(
                bufs["v_scale"], (li, 0, start, 0, 0),
                (1, b, block, kv, 1))[0, ..., 0]                # [B, S, KV]
            p_eff = p * vsb.transpose(0, 2, 1)[:, :, None, None, :]
        else:
            p_eff = p
        pv = jnp.einsum("bkgqs,bskd->bkgqd", p_eff.astype(vb.dtype), vb,
                        preferred_element_type=jnp.float32)
        acc = acc * alpha[..., None] + pv
        return new_m, l, acc

    m, l, acc = jax.lax.fori_loop(lo, n_active, body, (m0, l0, acc0))
    o = acc / l[..., None]          # l > 0: every query attends itself
    o = o.transpose(0, 3, 1, 2, 4).reshape(b, n_q, h, d)
    return o.astype(q.dtype)


def _cached_attention(q, bufs, li, q_start, attn_window=None):
    """q: [B, K, H, hd] holding positions q_start..q_start+K-1; ``bufs``:
    the cache's stacked [L, B, max_len, KV, hd] k/v buffers (plus
    ``k_scale``/``v_scale`` for int8 caches) with ``li`` this layer's
    static index (KV = H for MHA; KV < H for grouped-query, where each
    query group reads its shared K/V head WITHOUT materializing a
    repeated cache — the bandwidth saving is the point of GQA decode).
    Query i attends cache positions <= q_start+i (causal within the
    chunk, full history before it). Operands stay in the cache dtype
    (bf16 on TPU; int8 casting to the compute dtype in-dot for quantized
    caches) with f32 accumulation — casting the whole cache to f32 would
    double the hot loop's HBM traffic and halve MXU throughput.

    Large caches (max_len >= ``_BLOCKWISE_MIN_LEN``) dispatch to the
    length-aware block-wise path so serving cost follows the live length
    rather than the padded buffer."""
    k_all, v_all = bufs["k"], bufs["v"]
    max_len = k_all.shape[2]
    if max_len >= _BLOCKWISE_MIN_LEN:
        return _cached_attention_blockwise(q, bufs, li, q_start,
                                           attn_window=attn_window)
    quant = "k_scale" in bufs
    k_cache, v_cache = k_all[li], v_all[li]
    if quant:
        k_cache, v_cache = (k_cache.astype(q.dtype),
                            v_cache.astype(q.dtype))
    b, n_q, h, d = q.shape
    kv = k_cache.shape[2]
    group = h // kv                                  # 1 = plain MHA
    scale = d ** -0.5
    q_pos = _q_positions(q_start, b, n_q)                       # [B, Q]
    k_pos = jnp.arange(max_len)                                 # [S]
    mask = k_pos[None, None, :] <= q_pos[:, :, None]            # [B, Q, S]
    if attn_window is not None:
        mask = mask & (q_pos[:, :, None] - k_pos[None, None, :]
                       < attn_window)
    qg = q.reshape(b, n_q, kv, group, d)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    if quant:
        # per-token K scale is constant along the contracted hd — apply
        # it on the scores instead of dequantizing the cache
        ks = bufs["k_scale"][li, ..., 0].transpose(0, 2, 1)     # [B, KV, S]
        scores = scores * ks[:, :, None, None, :]
    scores = jnp.where(mask[:, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)                     # f32
    if quant:
        vs = bufs["v_scale"][li, ..., 0].transpose(0, 2, 1)     # [B, KV, S]
        probs = probs * vs[:, :, None, None, :]
    o = jnp.einsum("bkgqs,bskd->bqkgd", probs.astype(v_cache.dtype),
                   v_cache, preferred_element_type=jnp.float32)
    return o.reshape(b, n_q, h, d).astype(q.dtype)


def _ring_cached_attention(q, bufs, li, q_pos, attn_window: int):
    """Cached attention over a ROLLING cache [L, B, C, KV, hd]: writes
    wrapped modulo C, so ring row ``r`` holds the most recent absolute
    position congruent to r — ``q_pos - ((q_pos - r) mod C)``. A query
    at ``q_pos`` attends exactly the rows whose offset
    ``(q_pos - r) mod C`` is below ``min(attn_window, q_pos + 1)``:
    in-window history written by the CURRENT occupant (older residue in
    a reused slot can never satisfy the offset test — the slot-reuse
    argument of serve.py carries over row-wise). Dense over the C ring
    rows: C ≈ the window, the size regime where the dense einsum beats
    the blockwise walk anyway. Single-position queries only (K = 1 —
    the callers enforce it; chunked verify keeps the linear cache).

    q: [B, 1, H, hd]; q_pos: [B] absolute positions. Quantized caches
    fold their scales outside the dots exactly as the linear paths do."""
    k_all, v_all = bufs["k"], bufs["v"]
    quant = "k_scale" in bufs
    b, n_q, h, d = q.shape
    c = k_all.shape[2]
    k_cache, v_cache = k_all[li], v_all[li]
    if quant:
        k_cache, v_cache = (k_cache.astype(q.dtype),
                            v_cache.astype(q.dtype))
    kv = k_cache.shape[2]
    group = h // kv
    scale = d ** -0.5
    offset = jnp.mod(q_pos[:, None] - jnp.arange(c)[None, :], c)  # [B, C]
    mask = offset < jnp.minimum(attn_window, q_pos[:, None] + 1)
    qg = q.reshape(b, n_q, kv, group, d)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    if quant:
        ks = bufs["k_scale"][li, ..., 0].transpose(0, 2, 1)     # [B, KV, C]
        scores = scores * ks[:, :, None, None, :]
    scores = jnp.where(mask[:, None, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)                     # f32
    if quant:
        vs = bufs["v_scale"][li, ..., 0].transpose(0, 2, 1)
        probs = probs * vs[:, :, None, None, :]
    o = jnp.einsum("bkgqs,bskd->bqkgd", probs.astype(v_cache.dtype),
                   v_cache, preferred_element_type=jnp.float32)
    return o.reshape(b, n_q, h, d).astype(q.dtype)


def _window_write(buf_all, chunk, li, pos, window):
    """Bounded-window per-row cache write: the scatter-free alternative to
    ``.at[li, b, pos_b + j].set`` when per-row frontiers are guaranteed to
    lie within ``window`` positions of each other (max(pos) - min(pos) <=
    window - K — the caller's commit schedule enforces it).

    One contiguous ``window``-wide slice of the stacked cache is read,
    each row's K-token chunk lands at its own offset via a one-hot
    einsum (an MXU-shaped [B,W,K]x[B,K,KV*hd] contraction instead of a
    serialized gather/scatter), and the window is written back with one
    ``dynamic_update_slice``. Traffic is O(B * window) contiguous rows —
    independent of max_len and free of scatter lowering. Measured ~25%
    faster per speculative round than the global-cache scatter at the
    bench shapes (see docs/performance.md, round 5)."""
    b, n_k, kv, d = chunk.shape
    max_len = buf_all.shape[2]
    # clamp base the way dynamic_slice clamps its start (start <=
    # max_len - window), so `off` stays relative to where the slice
    # ACTUALLY lands. This clamp is LOAD-BEARING: near the end of
    # generation the draft writes' base sits up to k past the slowest
    # active row and the slice would run off the cache tail — the
    # caller's sizing argument (speculative_generate_device) only
    # guarantees the clamp shifts base by <= k-1 rows, which the
    # offsets absorb because they are computed against the CLAMPED base
    base = jnp.minimum(jnp.min(pos), max_len - window)
    # clip is a safety net only: the commit schedule keeps every offset
    # in [0, window - K] (window-invariant proof in
    # speculative_generate_device); a clipped frozen-row surrogate writes
    # garbage into that DEAD row's own cache, which nothing reads
    off = jnp.clip(pos - base, 0, window - n_k)                 # [B]
    w_idx = jnp.arange(window)
    sel = (w_idx[None, :, None]
           == off[:, None, None] + jnp.arange(n_k)[None, None, :])
    win = jax.lax.dynamic_slice(
        buf_all, (li, 0, base, 0, 0),
        (1, b, window, kv, d))[0]                               # [B, W, KV, hd]
    upd = jnp.einsum("bwj,bjkd->bwkd", sel.astype(chunk.dtype), chunk)
    win = jnp.where(sel.any(-1)[..., None, None], upd, win)
    return jax.lax.dynamic_update_slice(buf_all, win[None],
                                        (li, 0, base, 0, 0))


def _kv_writes(bufs: dict, k: jax.Array, v: jax.Array) -> dict:
    """The buffer→chunk map a K/V write must apply: plain k/v for float
    caches, quantized k/v plus their scale chunks for int8 caches. The
    single source of truth for the quantized write layout — shared by
    the decode blocks and prefill."""
    if "k_scale" in bufs:
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)
        return {"k": kq, "k_scale": ks, "v": vq, "v_scale": vs}
    return {"k": k, "v": v}


def _write_kv_chunk(buf, chunk, li, pos, window):
    """Write a K-token chunk [B, K, KV, d] into the stacked cache buffer
    [L, B, max_len, KV, d] at layer ``li``, positions ``pos``. The three
    write modes (scalar contiguous slice / bounded window / per-row
    unique scatter) are dtype- and trailing-dim-agnostic, so int8 caches
    route their [.., KV, 1] scale buffers through the same path as k/v."""
    if pos.ndim == 0:                   # uniform frontier: contiguous slice
        return jax.lax.dynamic_update_slice(buf, chunk[None],
                                            (li, 0, pos, 0, 0))
    if window is not None:              # bounded divergence: window write
        return _window_write(buf, chunk, li, pos, window)
    # per-row frontiers: unique scatter
    b_idx = jnp.arange(chunk.shape[0])[:, None]
    s_idx = pos[:, None] + jnp.arange(chunk.shape[1])[None, :]
    return buf.at[li, b_idx, s_idx].set(chunk, unique_indices=True)


def _decode_block(x, layer_params, bufs, li, pos, cfg, rope,
                  window=None):
    """Chunked decoder block. x: [B, K, D] at positions pos..pos+K-1;
    ``bufs``: the FULL stacked cache buffers [L, B, max_len, KV, hd]
    (k/v, plus scales for int8 caches); ``li``: this layer's static
    index; ``rope``: (cos, sin) tables precomputed once per chunk
    (position-only, so layer-invariant — same hoisting as the training
    forward). Writes only the K-token slice into the stacked cache (a
    layer-scan carrying the caches as xs/ys instead forced XLA to COPY
    the whole cache every decode step — the xs and ys buffers of a scan
    cannot alias — which dominated decode wall-clock). ``window``
    (static) selects the bounded-window write for vector ``pos`` whose
    rows the caller keeps within the window — see :func:`_window_write`.
    Returns (x, bufs)."""
    p = layer_params
    cos, sin = rope

    h = rms_norm_reference(x, p["attn_norm"])
    q = _weinsum("bsd,dhk->bshk", h, p["wq"])
    k = _weinsum("bsd,dhk->bshk", h, p["wk"])
    v = _weinsum("bsd,dhk->bshk", h, p["wv"])
    q, k = T.apply_rope(q, cos, sin), T.apply_rope(k, cos, sin)
    # write this chunk into the stacked cache (in place under jit: the
    # pre-update buffer has no later consumer)
    pos = jnp.asarray(pos)
    cap = _ring_capacity(cfg)
    if cap:
        # rolling cache: the write position wraps modulo the capacity
        # (single-token chunks only — _blocks_forward enforces it), and
        # the read masks rows by their ring offset from each query
        bufs = {n: _write_kv_chunk(bufs[n], c, li, pos % cap, None)
                for n, c in _kv_writes(bufs, k, v).items()}
        q_pos = (jnp.broadcast_to(pos, (x.shape[0],))
                 if pos.ndim == 0 else pos)
        o = _ring_cached_attention(q, bufs, li, q_pos, cfg.attn_window)
    else:
        bufs = {n: _write_kv_chunk(bufs[n], c, li, pos, window)
                for n, c in _kv_writes(bufs, k, v).items()}
        o = _cached_attention(q, bufs, li, pos,
                              attn_window=cfg.attn_window or None)
    x = x + _weinsum("bshk,hkd->bsd", o, p["wo"])

    h = rms_norm_reference(x, p["mlp_norm"])
    mlp_out = _mlp(h, p, cfg)
    return x + mlp_out, bufs


def _mlp(h, p, cfg):
    """Dense SwiGLU or MoE feed-forward on [B, S, D] (same params as the
    training block, transformer._block).

    MoE caveat: routing capacity scales with the LOCAL sequence length, so
    single-position decode (S=1, capacity >= top_k) never drops tokens while
    a full forward at low ``moe_capacity_factor`` may — cached generation
    can then diverge from the training forward on overflow tokens. This is
    the standard gshard trade; raise the capacity factor if you need exact
    equivalence."""
    if "router" in p:
        out, _ = moe_ffn(h, p["router"], p["w_gate"], p["w_down"],
                         top_k=cfg.moe_top_k,
                         capacity_factor=cfg.moe_capacity_factor,
                         activation=jax.nn.silu)
        return out
    gate = _weinsum("bsd,df->bsf", h, p["w_gate"])
    up = _weinsum("bsd,df->bsf", h, p["w_up"])
    return _weinsum("bsf,fd->bsd", jax.nn.silu(gate) * up, p["w_down"])


def _blocks_forward(params: dict, tokens: jax.Array, cache: dict, pos,
                    cfg: T.TransformerConfig,
                    window: int | None = None) -> tuple[jax.Array, dict]:
    """Run the decoder blocks over a K-token chunk, writing its K/V into
    the cache. Returns (block output x [B, K, D], updated cache) — the
    shared body of :func:`extend_step` and the head-free K/V write the
    device speculative loop uses (its eager last draft step discards the
    logits, so paying the lm_head vocab projection there is pure waste)."""
    x = params["embed"][tokens].astype(cfg.dtype)              # [B, K, D]
    b, n_q = tokens.shape
    if _ring_capacity(cfg) and n_q > 1:
        raise ValueError(
            "rolling KV cache (kv_cache_capacity) supports single-token "
            "decode steps only — chunked verify (speculative decoding) "
            "needs the linear cache")
    positions = _q_positions(pos, b, n_q)           # scalar or per-row pos
    rope = T.rope_tables(positions, cfg.head_dim)   # once, not per layer

    # Unrolled layer loop with static per-layer indices — NOT a lax.scan
    # with the caches as xs/ys (see _decode_block: scan forces whole-cache
    # copies every step)
    bufs = _kv_bufs(cache)
    for li in range(cfg.n_layers):
        layer_params = jax.tree.map(lambda a: a[li], params["blocks"])
        x, bufs = _decode_block(
            x, layer_params, bufs, li, pos, cfg, rope, window)
    return x, dict(bufs, length=pos + tokens.shape[1])


def extend_step(params: dict, tokens: jax.Array, cache: dict, pos,
                cfg: T.TransformerConfig,
                window: int | None = None) -> tuple[jax.Array, dict]:
    """Extend the cache with a K-token chunk at positions pos..pos+K-1.
    tokens: [B, K] int32; returns (logits [B, K, V] in
    cfg.logits_storage_dtype — logits[:, i] is the next-token distribution
    AFTER tokens[:, :i+1] — and the updated cache), rounded EXACTLY like
    the training forward so greedy decode agrees with it token for token.
    The chunked verify primitive for speculative decoding; K=1 is the
    plain decode step. ``window`` (static; vector ``pos`` only) routes
    the K/V writes through the bounded-window path —
    :func:`_window_write`."""
    x, new_cache = _blocks_forward(params, tokens, cache, pos, cfg, window)
    x = rms_norm_reference(x, params["final_norm"])
    logits = _weinsum("bsd,dv->bsv", x, params["lm_head"],
                      pet=jnp.float32)
    logits = logits.astype(cfg.logits_storage_dtype)
    return logits, new_cache


def decode_step(params: dict, token: jax.Array, cache: dict, pos,
                cfg: T.TransformerConfig,
                window: int | None = None) -> tuple[jax.Array, dict]:
    """One decode step. token: [B] int32; returns (logits [B, V] in
    cfg.logits_storage_dtype, updated cache). ``pos`` is the position
    being written (traced ok)."""
    logits, new_cache = extend_step(params, token[:, None], cache, pos, cfg,
                                    window)
    return logits[:, 0], new_cache


def _pad_prompts() -> bool:
    """Whether prefill right-pads prompts to flash-block-aligned lengths
    (needed on TPU; a seam so the CPU tests can force the padding path
    and pin its slicing/last-position logic)."""
    return jax.default_backend() == "tpu"


def _flash_safe_len(s: int) -> int:
    """Smallest sequence length >= s the TPU flash kernels accept: any
    length up to 256 tiles (block_q clamps to s; sub-128-lane cases fall
    back to dense attention inside flash_attention), lengths up to 1024
    must tile the 256-wide q blocks, and longer ones must tile the
    1024-wide kv blocks."""
    if s <= 256:
        return s
    if s <= 1024:
        return -(-s // 256) * 256
    return -(-s // 1024) * 1024


def prefill(params: dict, tokens: jax.Array, cfg: T.TransformerConfig,
            max_len: int) -> tuple[jax.Array, dict]:
    """Process the whole prompt in one forward, filling the cache.
    tokens: [B, S]; returns (last-position logits [B, V] in
    cfg.logits_storage_dtype, cache).

    Arbitrary prompt lengths: the TPU flash kernels need block-aligned
    sequences, so the forward runs at :func:`_flash_safe_len` with the
    prompt right-padded by zeros — causal masking keeps every REAL
    position's output independent of the padding tail, and only the real
    S rows of K/V are written to the cache (the returned logits read
    position S-1, not the padded end). Serving prompts are whatever
    length users send; without this, any prompt past 256 tokens that
    didn't tile the blocks raised at trace time. Caveat: with MoE
    layers, padded tokens still occupy router capacity (capacity scales
    with the PADDED length), so extreme padding can shift routing-drop
    behavior at low capacity factors."""
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, max_len)
    x, bufs = _prompt_forward(params, tokens, cfg, _kv_bufs(cache), s)
    logits = _weinsum("bd,dv->bv", x[:, s - 1], params["lm_head"],
                      pet=jnp.float32)
    logits = logits.astype(cfg.logits_storage_dtype)
    return logits, dict(bufs, length=jnp.asarray(s, jnp.int32))


def _prompt_forward(params, tokens, cfg, bufs, s):
    """The prompt forward shared by :func:`prefill` and
    :func:`prefill_rows`: right-pads ``tokens`` [B, s] to a flash-safe
    length when the kernels need it, runs the unrolled layer loop writing
    positions [0, s) of K/V into ``bufs``, and returns the final-norm'd
    activations [B, s_padded, D] plus the filled buffers — each caller
    does its own lm_head projection (last position for prefill, per-row
    true last positions for the bucketed variant)."""
    b = tokens.shape[0]
    sp = _flash_safe_len(s) if _pad_prompts() else s
    if sp != s:
        tokens = jnp.pad(tokens, ((0, 0), (0, sp - s)))
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(sp), (b, sp))
    cos, sin = T.rope_tables(positions, cfg.head_dim)   # once, not per layer

    # Unrolled layers, prompt K/V written straight into the stacked cache
    # (same no-scan rationale as extend_step; int8 caches quantize at the
    # write — the prefill forward itself runs full-precision)
    for li in range(cfg.n_layers):
        p = jax.tree.map(lambda a: a[li], params["blocks"])
        h = rms_norm_reference(x, p["attn_norm"])
        q = _weinsum("bsd,dhk->bshk", h, p["wq"])
        k = _weinsum("bsd,dhk->bshk", h, p["wk"])
        v = _weinsum("bsd,dhk->bshk", h, p["wv"])
        q, k = T.apply_rope(q, cos, sin), T.apply_rope(k, cos, sin)
        # GQA K/V go to the kernels unexpanded (flash/reference consume
        # kv_heads-wide K/V natively; no-op distinction for MHA)
        o = T._attention(q, k, v, None, window=cfg.attn_window or None)
        x = x + _weinsum("bshk,hkd->bsd", o, p["wo"])
        h = rms_norm_reference(x, p["mlp_norm"])
        x = x + _mlp(h, p, cfg)
        cap = _ring_capacity(cfg)
        if cap:
            # rolling cache: only the last min(s, cap) prompt positions
            # survive (everything older is outside every future query's
            # window anyway — cap >= attn_window); each lands at its
            # ring slot position % cap
            s0 = max(s - cap, 0)
            idx = jnp.arange(s0, s) % cap
            for n, c in _kv_writes(bufs, k[:, s0:s], v[:, s0:s]).items():
                layer = bufs[n][li].at[:, idx].set(c, unique_indices=True)
                bufs[n] = bufs[n].at[li].set(layer)
        else:
            for n, c in _kv_writes(bufs, k[:, :s], v[:, :s]).items():
                bufs[n] = _write_kv_chunk(bufs[n], c, li,
                                          jnp.asarray(0, jnp.int32), None)
    return rms_norm_reference(x, params["final_norm"]), bufs


def prefill_rows(params: dict, tokens: jax.Array, lengths: jax.Array,
                 cfg: T.TransformerConfig) -> tuple[jax.Array, dict]:
    """BUCKETED multi-prompt prefill: process K prompts right-padded to
    one shared bucket length in a single forward, compiling once per
    bucket instead of once per distinct prompt length. tokens: [K, S_b]
    int32 with each row's real prompt in its first ``lengths[k]``
    positions (``lengths`` is TRACED — any mix of real lengths reuses
    the bucket's compiled program); returns (per-row last-REAL-position
    logits [K, V], mini cache of S_b rows with per-row frontiers at the
    true lengths).

    Correctness of the padding tail: causal masking keeps every real
    position's output independent of the positions after it (the same
    argument :func:`prefill` makes for flash-block padding), and the
    padding rows' K/V beyond each row's frontier are unreachable by any
    future query — decode writes position ``lengths[k]`` before reading
    it, overwriting the first padding row, and queries attend positions
    <= their own only (the serve.py slot-reuse argument). MoE caveat as
    in :func:`prefill`: padded tokens still occupy router capacity.

    Rolling caches are rejected: ring writes wrap padded positions onto
    live rows (padding at position p lands on ring row p % C, clobbering
    real history), so ring configs keep the per-length admission path."""
    _check_no_ring(cfg, "bucketed prefill")
    k_rows, s = tokens.shape
    cache = init_kv_cache(cfg, k_rows, s)
    x, bufs = _prompt_forward(params, tokens, cfg, _kv_bufs(cache), s)
    xl = x[jnp.arange(k_rows), lengths - 1]                   # [K, D]
    logits = _weinsum("bd,dv->bv", xl, params["lm_head"],
                      pet=jnp.float32)
    return (logits.astype(cfg.logits_storage_dtype),
            dict(bufs, length=lengths.astype(jnp.int32)))


def place_rows(cache: dict, mini: dict, rows: jax.Array,
               lengths: jax.Array) -> dict:
    """Land a K-row mini cache's K/V into cache slots ``rows`` — the
    multi-row counterpart of serve.py's single-slot placement: one
    scatter on the batch axis per buffer (k/v plus int8 scales) covering
    positions [0, S_b), and the slots' frontiers set to their true
    ``lengths``. Out-of-range row indices are DROPPED (standard jit
    scatter semantics) — the batched admission path pads its row vector
    with distinct out-of-range sentinels, so a partial admission batch
    writes exactly its real rows."""
    s_b = mini["k"].shape[2]
    placed = {n: cache[n].at[:, rows, :s_b].set(
                  mini[n], mode="drop", unique_indices=True)
              for n in _kv_bufs(mini)}
    return dict(placed, length=cache["length"].at[rows].set(
        lengths.astype(jnp.int32), mode="drop", unique_indices=True))


def extract_kv_rows(mini: dict, widths) -> list[dict]:
    """Per-row HOST copies of a K-row mini cache's buffers — the
    extraction half of disaggregated serving's KV shipment
    (:func:`place_rows` is the landing half). Row ``i`` ships its first
    ``widths[i]`` positions of every buffer (k/v plus int8 scales when
    the cache is quantized — quantized caches ship their int8 payload
    as-is, never dequantized): for linear caches that is the true
    prompt length — the bucket-padding tail past the frontier is
    unreachable garbage, so shipping it would double the bytes of a
    short prompt for nothing — and for rolling (ring) caches the full
    capacity, whose positional wrap only a whole-slot landing
    preserves. Returns one ``{name: np [L, 1, w, KV, hd]}`` dict per
    row (every layer's slice in one device fetch per row)."""
    bufs = _kv_bufs(mini)
    out = []
    for i, w in enumerate(widths):
        w = int(w)
        out.append(jax.device_get(
            {n: b[:, i:i + 1, :w] for n, b in bufs.items()}))
    return out


def _filter_logits(logits, temperature: float, top_k: int, top_p: float):
    """The sampling filter stack on [..., V] f32 logits: top-k mask →
    temperature → top-p nucleus mask (keep the smallest prefix of the
    temperature-scaled distribution whose cumulative probability reaches
    ``top_p``; the crossing token stays; ties at the cutoff logit are
    all kept — the usual trade for a sort-free vocab-order mask; 0
    disables). Returns unnormalized log-space logits whose softmax IS
    the sampling distribution — shared by ad-hoc sampling
    (:func:`_sample`) and speculative SAMPLING, where the accept ratio
    must be computed against exactly the filtered distributions both
    models sample from. ``temperature`` must be > 0 here (the greedy
    case never needs a distribution)."""
    if top_k > 0:
        vals, _ = jax.lax.top_k(logits, top_k)
        cutoff = vals[..., -1][..., None]
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    scaled = logits / temperature
    if 0.0 < top_p < 1.0:
        desc = -jnp.sort(-scaled, axis=-1)                   # descending
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep positions whose PRECEDING mass is < top_p (the crossing
        # token stays; position 0 always kept)
        kept = (cum - probs) < top_p
        last = kept.sum(axis=-1) - 1
        cut = jnp.take_along_axis(desc, last[..., None], axis=-1)
        scaled = jnp.where(scaled < cut, -jnp.inf, scaled)
    return scaled


def _sample(logits, rng, temperature: float, top_k: int,
            top_p: float = 0.0):
    """logits [B, V] → (token [B], logprob [B]). Math in f32 whatever the
    storage dtype. Filters compose per :func:`_filter_logits`.

    The returned logprob is the MODEL's log p(token) — computed from the
    raw logits, before any masking or temperature — so it is usable for
    perplexity / importance weights regardless of sampling settings."""
    logits = logits.astype(jnp.float32)
    model_logp = jax.nn.log_softmax(logits, axis=-1)
    if temperature == 0.0:
        # top-k cannot change an argmax (the argmax is in every top-k)
        token = jnp.argmax(logits, axis=-1)
    else:
        token = jax.random.categorical(
            rng, _filter_logits(logits, temperature, top_k, top_p),
            axis=-1)
    return token, jnp.take_along_axis(model_logp, token[:, None],
                                      axis=-1)[:, 0]


def _check_no_ring(cfg, what: str):
    """Entry points whose cache discipline needs the LINEAR cache
    (chunked verifies, beam gathers, prefix templates) reject rolling
    caches up front — a silent wrong-output would be far worse."""
    if _ring_capacity(cfg):
        raise ValueError(f"{what} requires a linear KV cache; unset "
                         f"kv_cache_capacity (rolling cache) for it")


def _check_draft_vocab(cfg, draft_cfg):
    """Speculation compares TOKEN IDS between draft and target, so the
    two models must share a vocabulary. A mismatch is silent corruption
    in greedy mode (target ids past the draft's vocab clamp in its
    embedding gather, producing garbage proposals) and a shape error in
    sampled mode — reject it up front."""
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError(
            f"draft vocab_size {draft_cfg.vocab_size} != target "
            f"vocab_size {cfg.vocab_size}: speculative decoding requires "
            f"a shared vocabulary (same tokenizer)")


def _propose_chunk(params, draft_params, t_cache, d_cache, pending,
                   pos_arg, cfg, draft_cfg, k, win, token_dtype,
                   propose, extra_xs):
    """The draft-propose + target-verify scaffold shared by the greedy
    and sampled speculative rounds: the draft runs ``k`` single steps
    following ``pending`` (a ``lax.scan``; the LAST proposal's K/V is
    written eagerly through the head-free block body), then the target
    verifies the k+1-wide chunk in one :func:`extend_step`.

    ``propose(logits, x)`` picks each draft step's next token from the
    draft's [B, V] logits (``x`` is that step's element of
    ``extra_xs`` — rng keys for sampling, unused for greedy) and
    returns ``(token [B], aux)``; the per-step ``aux`` pytrees come
    back stacked (the sampled round collects the draft's sampling
    distributions this way).

    ``pos_arg`` is the position handed to the decode stack — a scalar
    (uniform frontier fast path) or a [B] vector (per-row frontiers);
    ``win`` routes vector-position K/V writes through the bounded-window
    path. Returns ``(chunk [B, k+1], auxes, logits [B, k+1, V],
    t_cache, d_cache)`` with ``chunk[:, 0] == pending``."""
    b = pending.shape[0]

    def d_step(carry, xs):
        i, x = xs
        tok, cache = carry
        logits, cache = decode_step(draft_params, tok, cache,
                                    pos_arg + i, draft_cfg, win)
        # keep the carried length [B]-shaped: the scalar-pos fast path
        # (b==1) returns a scalar length, which would flip the scan
        # carry's type
        cache = dict(cache, length=jnp.broadcast_to(
            cache["length"], (b,)).astype(jnp.int32))
        nxt, aux = propose(logits, x)
        return (nxt.astype(token_dtype), cache), (tok, aux)

    (last, d_cache), (fed, auxes) = jax.lax.scan(
        d_step, (pending, d_cache), (jnp.arange(k), extra_xs))
    _, d_cache = _blocks_forward(draft_params, last[:, None],
                                 d_cache, pos_arg + k, draft_cfg, win)
    # proposed[0] == pending; drafts are proposed[1:]
    proposed = jnp.concatenate([fed, last[None]])           # [k+1, B]
    chunk = proposed.T                                      # [B, k+1]
    logits, t_cache = extend_step(params, chunk, t_cache, pos_arg, cfg,
                                  win)
    return chunk, auxes, logits, t_cache, d_cache


def _propose_and_verify(params, draft_params, t_cache, d_cache, pending,
                        pos_arg, cfg, draft_cfg, k, win, token_dtype):
    """One GREEDY speculative round, shared by
    :func:`speculative_generate_device` and the serving path
    (:class:`tony_tpu.models.serve`'s speculative batcher), built on
    :func:`_propose_chunk`. Returns ``(chunk [B, k+1],
    argmaxes [B, k+1], acc [B], t_cache, d_cache)`` where ``argmaxes``
    are the target's greedy continuations after each chunk prefix and
    ``acc`` is the per-row length of the longest draft prefix the
    target agreed with. The COMMIT decision (how much of the chunk each
    row keeps) is the caller's — generation clamps to budgets/windows,
    serving clamps to nothing."""
    chunk, _, logits, t_cache, d_cache = _propose_chunk(
        params, draft_params, t_cache, d_cache, pending, pos_arg, cfg,
        draft_cfg, k, win, token_dtype,
        propose=lambda lg, _: (jnp.argmax(lg, axis=-1), ()),
        extra_xs=jnp.zeros((k,), jnp.int32))
    argmaxes = jnp.argmax(logits, axis=-1).astype(token_dtype)
    # per-row accepted = longest prefix where draft matched target
    matches = (chunk[:, 1:] == argmaxes[:, :k]).astype(jnp.int32)
    acc = jnp.cumprod(matches, axis=1).sum(axis=1)          # [B], 0..k
    return chunk, argmaxes, acc, t_cache, d_cache


def _propose_and_verify_sampled(params, draft_params, t_cache, d_cache,
                                pending, pos_arg, cfg, draft_cfg, k, win,
                                token_dtype, rng, temperature, top_k,
                                top_p):
    """One SPECULATIVE-SAMPLING round (the rejection-sampling
    counterpart of :func:`_propose_and_verify`): the draft SAMPLES k
    tokens from its filtered distribution q, the target verifies the
    chunk once, and each proposal x_i is accepted with probability
    ``min(1, p_i(x_i)/q_i(x_i))`` — the classic scheme whose committed
    tokens are distributed EXACTLY as target-only sampling from the
    filtered p, for any draft. On the first rejection the round's extra
    token is drawn from the residual ``normalize(max(p - q, 0))``; on
    full acceptance, from the bonus position's p (equivalently: residual
    against q = 0). Both models' distributions run through the SAME
    filter stack (:func:`_filter_logits`) — filtering only p or only q
    would break the guarantee.

    Returns ``(chunk [B, k+1], extra [B], acc [B], t_cache, d_cache)``:
    ``chunk[:, :acc+1]`` are committable tokens and ``extra`` is the
    round's residual/bonus sample — the next ``pending`` when the caller
    commits the full ``acc + 1``. A caller clamping its commit BELOW
    ``acc + 1`` (budget/window) must take ``chunk[:, count]`` as pending
    instead: an accepted draft token is itself a faithful sample of
    p( · | chunk[:count]) — that is precisely what acceptance certifies
    — while ``extra`` belongs to the deeper position only.

    The accept test is ``u * q(x) < p(x)`` (never divides; q(x) > 0
    because x was sampled from q). All probability math in f32.

    ``rng`` may be one PRNGKey (a shared per-round stream — the
    generate-path callers) or a [B, 2] array of PER-ROW keys (the
    serving path: each slot's draws come from its own request-derived
    stream, so a request's sampled tokens are a function of (request,
    round index) alone — independent of which other requests share the
    batch or when admission happened, which is what lets the pipelined
    serve loop shift admission timing without changing outputs)."""
    b = pending.shape[0]
    per_row = rng.ndim == 2
    if per_row:
        trip = jax.vmap(lambda kk: jax.random.split(kk, 3))(rng)
        d_rng, u_rng, r_rng = trip[:, 0], trip[:, 1], trip[:, 2]
        # [k, B, 2]: scan xs of per-row draft-step keys
        d_xs = jax.vmap(lambda kk: jax.random.split(kk, k))(
            d_rng).transpose(1, 0, 2)
    else:
        d_rng, u_rng, r_rng = jax.random.split(rng, 3)
        d_xs = jax.random.split(d_rng, k)
    vocab = cfg.vocab_size

    def propose(logits, key):
        f = _filter_logits(logits.astype(jnp.float32), temperature,
                           top_k, top_p)
        tok = (jax.vmap(jax.random.categorical)(key, f) if per_row
               else jax.random.categorical(key, f, axis=-1))
        return tok, jax.nn.softmax(f, axis=-1)

    chunk, qs, logits, t_cache, d_cache = _propose_chunk(
        params, draft_params, t_cache, d_cache, pending, pos_arg, cfg,
        draft_cfg, k, win, token_dtype,
        propose=propose, extra_xs=d_xs)
    p = jax.nn.softmax(_filter_logits(logits.astype(jnp.float32),
                                      temperature, top_k, top_p),
                       axis=-1)                             # [B, k+1, V]
    x = chunk[:, 1:].astype(jnp.int32)[..., None]           # [B, k, 1]
    q_bkv = qs.transpose(1, 0, 2)                           # [B, k, V]
    qx = jnp.take_along_axis(q_bkv, x, axis=2)[..., 0]
    px = jnp.take_along_axis(p[:, :k], x, axis=2)[..., 0]   # [B, k]
    u = (jax.vmap(lambda kk: jax.random.uniform(kk, (k,)))(u_rng)
         if per_row else jax.random.uniform(u_rng, (b, k)))
    accept = (u * qx < px).astype(jnp.int32)
    acc = jnp.cumprod(accept, axis=1).sum(axis=1)           # [B], 0..k

    # residual/bonus at the decision position: q rows padded with a zero
    # slab so acc == k selects residual against 0, i.e. the bonus p
    sel = acc[:, None, None]                                # [B, 1, 1]
    p_sel = jnp.take_along_axis(p, sel, axis=1)[:, 0]       # [B, V]
    q_pad = jnp.concatenate(
        [q_bkv, jnp.zeros((b, 1, vocab), jnp.float32)], axis=1)
    q_sel = jnp.take_along_axis(q_pad, sel, axis=1)[:, 0]
    res = jnp.maximum(p_sel - q_sel, 0.0)
    # numeric guard: mathematically res sums to > 0 whenever a rejection
    # happened, but f32 cancellation can zero it — fall back to p
    res = jnp.where(res.sum(-1, keepdims=True) > 0, res, p_sel)
    extra = (jax.vmap(jax.random.categorical)(r_rng, jnp.log(res))
             if per_row
             else jax.random.categorical(r_rng, jnp.log(res), axis=-1)
             ).astype(token_dtype)
    return chunk, extra, acc, t_cache, d_cache


def speculative_generate(params: dict, draft_params: dict, prompt: jax.Array,
                         cfg: T.TransformerConfig,
                         draft_cfg: T.TransformerConfig,
                         max_new_tokens: int,
                         num_speculative: int = 4) -> jax.Array:
    """Greedy speculative decoding: a cheap draft model proposes
    ``num_speculative`` tokens per round; the target model verifies the
    whole chunk in ONE chunked :func:`extend_step` and commits the longest
    prefix matching its own argmax chain plus one corrected token — output
    is token-identical to the target model's greedy :func:`generate`, in
    (accepted+1) tokens per target call instead of 1.

    Batch size must be 1: acceptance length is data-dependent per row and
    the cache keeps a single scalar length. Returns tokens
    [1, prompt_len + max_new_tokens].

    Caveats (measured, not theoretical):
    - Exactness holds when chunked and single-step logits agree exactly.
      That is true where matmul accumulation is shape-independent (the
      CPU test path — bit-identical). On TPU, XLA's default matmul
      precision runs even f32 models through bf16 passes, so chunk vs
      single-step logits differ by ~1e-2 and a near-tie argmax can flip
      regardless of dtype: occasional tokens (and everything after the
      first flip) may differ from plain greedy — both are valid greedy
      decodes of the model within matmul noise.
    - This is a host-driven reference implementation: each round syncs with
      the device for the acceptance decision, so wall-clock wins require
      low host-device latency (it is NOT faster over remote/tunneled
      device transports, where every sync costs a network round trip).
    """
    b, s = prompt.shape
    if b != 1:
        raise ValueError("speculative_generate supports batch size 1")
    if num_speculative < 1:
        raise ValueError("num_speculative must be >= 1 (use generate() for "
                         "plain greedy decoding)")
    _check_draft_vocab(cfg, draft_cfg)
    _check_no_ring(cfg, "speculative decoding")
    _check_no_ring(draft_cfg, "speculative decoding (draft)")
    k = num_speculative
    max_len = s + max_new_tokens + k + 1
    t_logits, t_cache = prefill(params, prompt, cfg, max_len)
    _, d_cache = prefill(draft_params, prompt, draft_cfg, max_len)

    # Donate the cache: a non-donated jit input cannot alias its output, so
    # without this every call would copy the full stacked K/V buffers —
    # exactly the whole-cache-copy cost the unrolled layer loop removed
    # inside generate()'s single jit. Each round threads the returned cache
    # forward and never touches the donated input again.
    extend_t = jax.jit(extend_step, static_argnames=("cfg",),
                       donate_argnames=("cache",))
    step_d = jax.jit(decode_step, static_argnames=("cfg",),
                     donate_argnames=("cache",))

    out: list[int] = []
    # pending = committed token whose K/V is not yet in the target cache
    pending = int(jnp.argmax(t_logits, axis=-1)[0])
    pos = s
    while len(out) < max_new_tokens:
        # draft proposes k tokens following `pending` from its own cache
        drafts = []
        tok = jnp.array([pending])
        d_pos = pos
        for _ in range(k):
            d_logits, d_cache = step_d(draft_params, tok, d_cache, d_pos,
                                       draft_cfg)
            tok = jnp.argmax(d_logits, axis=-1)
            drafts.append(int(tok[0]))
            d_pos += 1
        # target verifies [pending, d1..dk] in one chunk
        chunk = jnp.array([[pending] + drafts])
        logits, t_cache = extend_t(params, chunk, t_cache, pos, cfg)
        argmaxes = jnp.argmax(logits[0], axis=-1)      # [k+1]
        out.append(pending)
        accepted = 0
        for i in range(k):
            if len(out) >= max_new_tokens:
                break
            if drafts[i] != int(argmaxes[i]):
                break
            out.append(drafts[i])
            accepted += 1
        new_pending = int(argmaxes[accepted])
        if accepted == k:
            # full acceptance: the draft cache never wrote d_k's K/V (it
            # produced d_k as output only); backfill so the next round's
            # history is complete.
            _, d_cache = step_d(draft_params, jnp.array([drafts[-1]]),
                                d_cache, pos + k, draft_cfg)
        # Roll both caches back to the committed frontier. Stale entries
        # from rejected drafts are NOT masked (attention reads positions
        # <= each query's own position, not the length field) — they are
        # safe only because the next round's k+1-wide chunk rewrites the
        # whole stale region (<= k entries) before any query can reach it.
        pos += 1 + accepted
        t_cache = dict(t_cache, length=jnp.asarray(pos, jnp.int32))
        d_cache = dict(d_cache, length=jnp.asarray(pos, jnp.int32))
        pending = new_pending
    tokens = jnp.array([out[:max_new_tokens]], dtype=prompt.dtype)
    return jnp.concatenate([prompt, tokens], axis=1)


@functools.partial(jax.jit, static_argnames=(
    "cfg", "draft_cfg", "max_new_tokens", "num_speculative", "commit",
    "window", "temperature", "top_k", "top_p", "return_rounds"))
def speculative_generate_device(params: dict, draft_params: dict,
                                prompt: jax.Array,
                                cfg: T.TransformerConfig,
                                draft_cfg: T.TransformerConfig,
                                max_new_tokens: int,
                                num_speculative: int = 4,
                                commit: str = "window",
                                window: int = 0,
                                temperature: float = 0.0,
                                top_k: int = 0,
                                top_p: float = 0.0,
                                rng: jax.Array | None = None,
                                return_rounds: bool = False) -> jax.Array:
    """Speculative decoding as ONE compiled device program — greedy
    (token-exact) by default, rejection-SAMPLED (distribution-exact)
    at ``temperature > 0``.

    The host-driven :func:`speculative_generate` syncs with the device
    every round for the acceptance decision — a network round trip per
    round on remote/tunneled transports. This version runs the whole
    draft→verify→accept loop inside a ``lax.while_loop``: the draft
    proposes ``k`` tokens (a ``lax.scan`` of single steps), the target
    verifies the k+1 chunk in one :func:`extend_step`, and the accepted
    prefix length is a cumulative-product reduction — no host in the
    loop. Output is token-identical to the target model's greedy
    :func:`generate` wherever chunked and single-step logits agree
    (bit-exact on CPU; on TPU the default matmul precision can flip
    near-tie argmaxes in any dtype — see :func:`speculative_generate`'s
    caveats). Any batch size — see the min-commit paragraph below.

    Measured on one v5e behind a network tunnel (small preset, 256 new
    tokens): this program decodes at ~1.6k tok/s while the host-driven
    version manages ~1 tok/s — each of its per-round device syncs pays
    the transport round trip, which is exactly what the while_loop
    removes. Wall-clock wins over plain :func:`generate` additionally
    require a draft that actually predicts the target (tokens/round ≈
    1 + acceptance·k); with a random draft this is a correctness
    demonstration, not a speedup. ``bench.py``'s arm trains a real
    draft and records 2.8-2.9× over batch-1 greedy.

    Batch > 1 uses PER-ROW CACHE FRONTIERS: acceptance length is
    data-dependent per row, so the cache ``length`` and every position
    argument generalize to [B] vectors — RoPE positions, causal masks,
    and the K/V writes all take per-row frontiers. Each row commits its
    OWN ``acc_r + 1`` tokens per round; no row waits for the batch
    minimum, so tokens/round does not decay as per-row acceptances
    diverge (the min-commit design this replaced decayed toward 1 with
    batch). Rows that reach ``max_new_tokens`` freeze (commit clamped
    to 0) while the rest finish. ``commit="per_row"`` writes each row's
    K/V at its own frontier with a unique-index scatter; the default
    ``commit="window"`` (below) keeps per-row commits but replaces the
    scatter with a bounded-window write — measured faster at every
    acceptance level (interleaved medians, one v5e, b8 k=10: +7% at
    near-perfect acceptance, +4.5% at a mediocre draft, +36% over
    min-commit).

    ``commit="min"`` restores the decayed min-commit schedule (every row
    commits the batch-minimum acceptance) — kept as the measured baseline
    for the bench's acceptance sweep, not for production use.
    ``return_rounds=True`` additionally returns the number of
    draft→verify rounds executed (tokens/round = the speculation
    efficiency the sweep records).

    ``commit="window"`` (the default) is per-row commit with the cache
    writes routed
    through the scatter-free bounded-window path (:func:`_window_write`):
    rows commit their own acceptance like ``per_row``, EXCEPT a row more
    than ``window - (k+1)`` positions ahead of the slowest active row is
    clamped to that bound, so every round's writes land inside one
    ``window``-wide contiguous slice of the cache (one dynamic slice +
    an MXU one-hot merge instead of a global-cache scatter). ``window``
    defaults to ``4*(k+1)`` — wide enough that clamping only bites when
    per-row acceptances diverge persistently, at which point the
    schedule degrades gracefully toward min-commit rather than paying
    the scatter. Rows that finish FREEZE their true frontier and are
    excluded from the window base (no drag-along writes are needed: a
    frozen row's surrogate position is clipped into the active window
    and its writes are garbage into its own dead cache rows, which
    nothing reads — its committed tokens already live in the output
    buffer). Window-invariant (enforced each round, relied on by
    ``_window_write``): for every active row,
    ``pos_r - min(active pos) <= window - (k+1)``; the clamp preserves
    it because the slowest active row is never clamped and every other
    row is cut to exactly the bound. Token-identical to greedy, same as
    the other schedules (test-verified, including forced-clamp windows).

    Cache discipline (static shapes throughout): the target's stale
    entries from rejected drafts are overwritten by the next round's
    k+1-wide per-row chunk before any query can reach them (same argument
    as the host version, applied row-wise); the draft runs k+1 steps per
    round — the last proposal's K/V is written eagerly — so full
    acceptance needs no backfill branch. The token buffer is written with
    full k+1-wide rows at each row's own offset: positions past the
    committed count are garbage that the next round's write (which starts
    exactly there) or the final slice removes.

    ``temperature > 0`` switches every round to SPECULATIVE SAMPLING
    (:func:`_propose_and_verify_sampled`, rng required): the draft
    samples its proposals, the target accept/rejects each with the
    classic ``min(1, p/q)`` test, and the committed stream is
    distributed exactly as target-only sampling through the same
    ``top_k``/``top_p`` filter stack as :func:`generate` — for ANY
    draft (a bad draft costs rounds, never correctness;
    distribution-verified against direct sampling in the tests). All
    commit schedules compose: a row clamped below its acceptance takes
    the accepted draft token at the cut as its next pending (itself a
    faithful sample at that position), the unclamped row takes the
    round's residual/bonus sample.
    """
    b, s = prompt.shape
    k = num_speculative
    if k < 1:
        raise ValueError("num_speculative must be >= 1")
    if commit not in ("per_row", "min", "window"):
        raise ValueError(f"unknown commit policy {commit!r}")
    if temperature > 0.0 and rng is None:
        raise ValueError("speculative sampling (temperature > 0) "
                         "requires an rng key")
    _check_draft_vocab(cfg, draft_cfg)
    _check_no_ring(cfg, "speculative decoding")
    _check_no_ring(draft_cfg, "speculative decoding (draft)")
    if commit == "window":
        # default + validate at ANY batch size (a window accepted at b=1
        # must not start raising when the batch widens), though the
        # window write itself only engages at b > 1
        window = window or 4 * (k + 1)
        if window < k + 2:
            raise ValueError(f"window must be >= num_speculative + 2 "
                             f"(chunk width k+1 plus >= 1 slack), got "
                             f"{window}")
    if commit == "window" and b > 1:
        # `window` rows of tail padding suffice: the target-chunk write's
        # base (= the slowest active row, < s+max_new_tokens) never
        # clamps, and the draft writes' base (+i <= +k) clamps by at most
        # c = amin + k - (s+max_new_tokens) <= k-1 rows, which keeps
        # every K=1 offset at slack + c <= window - 2 — inside the
        # window (_window_write computes offsets AGAINST the clamped
        # base, so a clamp shifts the slice, not the write positions).
        # Oversizing further would inflate the padded cache the dense
        # attention path reads every step.
        max_len = s + max_new_tokens + window
    else:
        # includes commit="window" at b==1: the window path is a no-op
        # there (win=None routes to the scalar contiguous-slice writes),
        # so take per_row's k+2 padding rather than inflating the padded
        # cache the dense attention reads every step
        max_len = s + max_new_tokens + k + 2
    #: per-row divergence bound in window mode (chunk is k+1 wide)
    slack = window - (k + 1)
    t_logits, t_cache = prefill(params, prompt, cfg, max_len)
    _, d_cache = prefill(draft_params, prompt, draft_cfg, max_len)
    # per-row frontiers: vectorize the scalar length prefill produced so
    # the while_loop state pytree is shape-stable across rounds
    t_cache = dict(t_cache, length=jnp.full((b,), s, jnp.int32))
    d_cache = dict(d_cache, length=jnp.full((b,), s, jnp.int32))

    # new tokens land here; k+1 slack for the final round's overshoot
    # (commits clamp so no row's write can start past max_new_tokens)
    buf0 = jnp.zeros((b, max_new_tokens + k + 1), prompt.dtype)
    if temperature > 0.0:
        rng, p0_rng = jax.random.split(rng)
        pending0 = jax.random.categorical(
            p0_rng, _filter_logits(t_logits.astype(jnp.float32),
                                   temperature, top_k, top_p),
            axis=-1).astype(prompt.dtype)
    else:
        pending0 = jnp.argmax(t_logits, axis=-1).astype(prompt.dtype)

    def _pos_arg(pos):
        """Position argument for the decode stack: at batch 1 per-row and
        uniform frontiers coincide, so hand the cache writers the SCALAR
        form — the contiguous dynamic_update_slice path instead of the
        scatter, which measured ~17% slower end-to-end at b1."""
        return pos[0] if b == 1 else pos

    # static per-call write mode: bounded-window writes only make sense
    # for genuinely per-row (vector) positions
    win = window if (commit == "window" and b > 1) else None

    def round_body(state):
        if temperature > 0.0:
            (t_cache, d_cache, buf, n_gen, pending, pos, rounds,
             cur_rng) = state
            cur_rng, round_rng = jax.random.split(cur_rng)
        else:
            t_cache, d_cache, buf, n_gen, pending, pos, rounds = state

        if win is not None:
            # frozen rows (n_gen == max_new_tokens) are excluded from the
            # window base — otherwise their pinned frontier would stall
            # the window and deadlock the still-active rows — and fed a
            # surrogate position clipped into the active window (their
            # writes/reads are garbage in dead rows; see docstring)
            active = n_gen < max_new_tokens
            amin = jnp.min(jnp.where(active, pos, jnp.iinfo(jnp.int32).max))
            pos_fed = jnp.where(active, pos,
                                jnp.clip(pos, amin, amin + slack))
        else:
            pos_fed = pos

        if temperature > 0.0:
            chunk, extra, acc, t_cache, d_cache = (
                _propose_and_verify_sampled(
                    params, draft_params, t_cache, d_cache, pending,
                    _pos_arg(pos_fed), cfg, draft_cfg, k, win,
                    prompt.dtype, round_rng, temperature, top_k, top_p))
        else:
            chunk, argmaxes, acc, t_cache, d_cache = _propose_and_verify(
                params, draft_params, t_cache, d_cache, pending,
                _pos_arg(pos_fed), cfg, draft_cfg, k, win, prompt.dtype)
        # per-row commit, clamped so finished rows freeze and no write
        # can overrun the buffer slack
        committed = jnp.min(acc) if commit == "min" else acc
        count = jnp.minimum(committed + 1, max_new_tokens - n_gen)  # [B]
        if win is not None:
            # window clamp: no row may end the round more than `slack`
            # past the slowest still-active row. The min-achieving row is
            # never clamped (count_min + slack >= count_min), so the
            # post-clamp active minimum EQUALS amin_next and the window
            # invariant holds next round; every active row still
            # advances >= 1 (amin_next >= amin + 1 and pos <= amin +
            # slack give the bound >= 1), so the loop terminates.
            amin_next = jnp.min(jnp.where(active, pos + count,
                                          jnp.iinfo(jnp.int32).max))
            # min-then-max (not clip): a frozen row ahead of the window
            # has a NEGATIVE bound, and clip(x, 0, neg) is neg under
            # numpy semantics — the max(..., 0) keeps its count frozen
            count = jnp.maximum(
                jnp.minimum(count, amin_next + slack - pos), 0)
        b_idx = jnp.arange(b)[:, None]
        buf = buf.at[b_idx, n_gen[:, None] + jnp.arange(k + 1)[None]].set(
            chunk, unique_indices=True)
        if temperature > 0.0:
            # committed in full: the residual/bonus sample continues the
            # stream; clamped below acc+1: the accepted draft token AT
            # the cut is itself a faithful sample there (see
            # _propose_and_verify_sampled)
            cont = jnp.take_along_axis(
                chunk, jnp.clip(count, 0, k)[:, None], axis=1)[:, 0]
            new_pending = jnp.where(
                count > 0, jnp.where(count == acc + 1, extra, cont),
                pending)
        else:
            sel = jnp.clip(count - 1, 0, k)
            corr = jnp.take_along_axis(argmaxes, sel[:, None],
                                       axis=1)[:, 0]
            new_pending = jnp.where(count > 0, corr, pending)
        n_gen = n_gen + count
        pos = pos + count
        # rollback: stale cache entries past each row's pos are rewritten
        # by the next round's chunk before any query reaches them
        t_cache = dict(t_cache, length=pos.astype(jnp.int32))
        d_cache = dict(d_cache, length=pos.astype(jnp.int32))
        out = (t_cache, d_cache, buf, n_gen, new_pending, pos, rounds + 1)
        return out + (cur_rng,) if temperature > 0.0 else out

    def cond(state):
        return jnp.min(state[3]) < max_new_tokens

    state0 = (t_cache, d_cache, buf0,
              jnp.zeros((b,), jnp.int32), pending0,
              jnp.full((b,), s, jnp.int32), jnp.asarray(0, jnp.int32))
    if temperature > 0.0:
        state0 = state0 + (rng,)
    final = jax.lax.while_loop(cond, round_body, state0)
    buf, rounds = final[2], final[6]
    tokens = jnp.concatenate([prompt, buf[:, :max_new_tokens]], axis=1)
    return (tokens, rounds) if return_rounds else tokens


class BeamSearchOutput(NamedTuple):
    tokens: jax.Array    # [B, W, prompt_len + max_new_tokens], best first
    scores: jax.Array    # [B, W] sum of token logprobs (length-penalized
    #                      ordering; raw sums reported)
    lengths: jax.Array   # [B, W] generated tokens incl. eos (= max_new
    #                      when no eos was hit)


@functools.partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "beam_width", "length_penalty", "eos_id"))
def beam_search(params: dict, prompt: jax.Array, cfg: T.TransformerConfig,
                max_new_tokens: int, beam_width: int = 4,
                length_penalty: float = 0.0,
                eos_id: int | None = None) -> BeamSearchOutput:
    """KV-cache beam search: keep the ``beam_width`` highest-logprob
    continuations per prompt, expanding all beams in one batched decode
    step per token. One compiled program (prefill + ``lax.scan``), same
    static-shape discipline as :func:`generate`.

    Mechanics: the prompt prefills once per row and its cache tiles
    across beams ([L, B, S, KV, hd] → [L, B·W, S, KV, hd] — beams share
    history until they diverge); each step feeds every beam's last token
    (writing its K/V), forms the [B, W·V] successor scores, takes the
    top W, and GATHERS the cache along the beam axis by parent index —
    the standard reorder, O(cache) per step, which is why beam search
    costs ~W× greedy plus the reorder traffic. Finished beams (``eos``)
    reproduce themselves with frozen scores via a one-hot candidate
    mask, so static shapes hold while they stop growing.

    Ranking uses ``score / length**length_penalty`` (0 = pure logprob;
    higher favors longer continuations); returned beams are sorted by
    that key, best first, with the RAW logprob sums in ``scores`` and
    per-beam generated lengths (including the eos token) in
    ``lengths``. Tokens after a beam's eos are padding (the eos token
    repeated) — slice with ``lengths`` if you need exact sequences.

    Reference: green-field (SURVEY.md §2.3 — the reference delegates
    all decoding); verified against exhaustive-enumeration search and a
    cache-free reimplementation in ``tests/test_decode.py``."""
    b, s = prompt.shape
    w = beam_width
    if w < 1:
        raise ValueError("beam_width must be >= 1")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    _check_no_ring(cfg, "beam search")
    v = cfg.vocab_size
    max_len = s + max_new_tokens
    logits, cache = prefill(params, prompt, cfg, max_len)

    # tile the prefilled cache across beams: [L, B, ...] -> [L, B*W, ...]
    # (all position buffers — k/v plus int8 scales when present)
    cache = dict({n: jnp.repeat(x, w, axis=1)
                  for n, x in _kv_bufs(cache).items()},
                 length=cache["length"])

    logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    scores, first = jax.lax.top_k(logp0, w)                  # [B, W]
    first = first.astype(prompt.dtype)
    alive0 = (jnp.ones((b, w), bool) if eos_id is None
              else first != eos_id)
    tok_buf0 = jnp.zeros((b, w, max_new_tokens), prompt.dtype)
    tok_buf0 = tok_buf0.at[:, :, 0].set(first)
    len0 = jnp.ones((b, w), jnp.int32)

    def step(carry, i):
        cache, scores, last, alive, tok_buf, lens = carry
        flat_last = last.reshape(b * w)
        logits, cache = decode_step(params, flat_last, cache,
                                    cache["length"], cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1).reshape(b, w, v)
        if eos_id is not None:
            # finished beams emit ONLY eos (a self-reproducing padding
            # token) at no score cost; everything else is -inf
            eos_only = jnp.full((v,), -jnp.inf).at[eos_id].set(0.0)
            logp = jnp.where(alive[..., None], logp, eos_only)
        cand = scores[..., None] + logp                      # [B, W, V]
        new_scores, idx = jax.lax.top_k(cand.reshape(b, w * v), w)
        parent = idx // v                                    # [B, W]
        token = (idx % v).astype(prompt.dtype)

        # reorder every per-beam tensor by parent; the cache gathers
        # along its flattened B*W axis
        gidx = (jnp.arange(b)[:, None] * w + parent).reshape(-1)
        cache = dict(cache, **{n: x[:, gidx]
                               for n, x in _kv_bufs(cache).items()})
        take = functools.partial(jnp.take_along_axis, indices=parent,
                                 axis=1)
        alive = take(alive)
        lens = take(lens)
        tok_buf = jnp.take_along_axis(
            tok_buf, parent[..., None], axis=1)
        tok_buf = tok_buf.at[:, :, i].set(token)
        lens = jnp.where(alive, lens + 1, lens)
        if eos_id is not None:
            alive = alive & (token != eos_id)
        return (cache, new_scores, token, alive, tok_buf, lens), None

    carry = (cache, scores, first, alive0, tok_buf0, len0)
    if max_new_tokens > 1:
        carry, _ = jax.lax.scan(step, carry,
                                jnp.arange(1, max_new_tokens))
    _, scores, _, _, tok_buf, lens = carry

    # order by the length-penalized key, report raw scores
    key = scores if length_penalty == 0.0 else (
        scores / (lens.astype(jnp.float32) ** length_penalty))
    order = jnp.argsort(-key, axis=1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    lens = jnp.take_along_axis(lens, order, axis=1)
    tok_buf = jnp.take_along_axis(tok_buf, order[..., None], axis=1)
    prompts = jnp.broadcast_to(prompt[:, None], (b, w, s))
    return BeamSearchOutput(
        tokens=jnp.concatenate([prompts, tok_buf], axis=2),
        scores=scores, lengths=lens)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "temperature", "top_k",
                                             "top_p"))
def generate(params: dict, prompt: jax.Array, cfg: T.TransformerConfig,
             max_new_tokens: int, rng: jax.Array,
             temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0) -> GenerateOutput:
    """Prefill + scan-decode. prompt: [B, S] int32. Greedy when
    temperature=0; ``top_k``/``top_p`` (nucleus) filters compose as in
    :func:`_sample`. One compiled program; re-traces only on new static
    shapes/config."""
    b, s = prompt.shape
    max_len = s + max_new_tokens
    logits, cache = prefill(params, prompt, cfg, max_len)

    def step(carry, step_rng):
        logits, cache = carry
        token, logp = _sample(logits, step_rng, temperature, top_k, top_p)
        new_logits, cache = decode_step(params, token, cache,
                                        cache["length"], cfg)
        return (new_logits, cache), (token, logp)

    rngs = jax.random.split(rng, max_new_tokens)
    _, (tokens, logprobs) = jax.lax.scan(step, (logits, cache), rngs)
    return GenerateOutput(
        tokens=jnp.concatenate([prompt, tokens.T], axis=1),
        logprobs=logprobs.T)
