"""Flagship decoder-only transformer LM, TPU-first.

The model family the framework's parallelism layer is designed around (the
reference ships only MNIST example scripts — tony-examples/ — because it
delegates all compute; SURVEY.md §2.3 flags TP/SP/CP/EP as green-field
obligations for this build). Design choices, each mapped to the hardware:

- **bfloat16 everywhere, f32 where it matters**: params/activations bf16 for
  MXU throughput; logits, softmax and loss in f32.
- **Stacked layers + lax.scan**: one compiled block body instead of L copies
  (compile time, icache); pairs with ``jax.checkpoint`` for remat and with
  the pipeline layer (same [L, ...] leading-stage layout).
- **Logical-axis annotations**: every param carries logical axes resolved by
  tony_tpu.parallel.sharding rules, so DP→FSDP→TP+SP→EP is a mesh/rule
  change, not a model change.
- **Flash attention** (tony_tpu.ops) on TPU; ring attention over the ``cp``
  mesh axis for long context; dense reference elsewhere.
- **RoPE** positions (no position-embedding table to shard), RMSNorm,
  SwiGLU MLP — the standard modern decoder block.
- Optional **MoE** MLP (gshard dispatch over ``ep``) per
  tony_tpu.parallel.moe.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tony_tpu.ops.attention import flash_attention, reference_attention
from tony_tpu.ops.norms import rms_norm_reference
from tony_tpu.parallel.moe import moe_ffn
from tony_tpu.parallel.ring_attention import ring_attention
from tony_tpu.parallel.sharding import DEFAULT_RULES, constrain
from tony_tpu.models.train import masked_cross_entropy


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    # Grouped-query attention: number of K/V heads (None = n_heads, plain
    # MHA). Shrinks K/V projections and — the real win — the decode cache
    # by n_heads/n_kv_heads; query heads attend their group's shared K/V.
    n_kv_heads: int | None = None
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    # Storage dtype of the [B, S, V] logits — the largest activation in the
    # step. Matmul accumulation and all loss math stay f32 regardless; only
    # the HBM round trip between them is rounded. bf16 halves that traffic
    # (+3-4% step throughput at 16×1024×32k on one v5e) and measured loss /
    # grad-norm agree with f32 storage to ~1e-5. None = follow ``dtype``
    # (bf16 models store bf16 logits, f32 models keep f32); set explicitly
    # to pin it.
    logits_dtype: Any = None
    remat: bool = True
    # Rematerialization policy when remat=True: "full" recomputes the
    # whole block in backward (minimum memory); "dots" saves the
    # NON-BATCHED matmul outputs — projections and MLP; the batched
    # attention QK^T/AV dots are still recomputed
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) — more
    # activation memory, but the backward stops re-paying the projection/
    # MLP FLOPs that dominate the recompute bill.
    remat_policy: str = "full"
    # lax.scan unroll factor over layers: 1 = rolled while-loop (fast
    # compile, the default); n_layers = fully unrolled (removes the scan's
    # activation-stacking dynamic-update-slices, ~6% faster per step on one
    # chip, slower compile). Any divisor of n_layers is valid.
    scan_unroll: int = 1
    # Context-parallel strategy when the mesh has a cp axis: "ring"
    # (ppermute K/V rotation, O(S/cp) memory, any head count) or "ulysses"
    # (two all-to-alls, full-seq attention on H/cp local heads).
    cp_strategy: str = "ring"
    # Sliding-window attention (Mistral-style local attention): each
    # query attends its `attn_window` most recent positions. 0 = full
    # causal. The flash kernels triage out-of-window blocks exactly like
    # above-diagonal ones (skipped compute + elided DMA), so fwd+bwd
    # attention cost scales with seq×window instead of seq²; the decode
    # blockwise path starts its cache walk at the window's first block,
    # making per-token serving cost O(window) regardless of history.
    # Not composable with context parallelism (cp > 1) yet.
    attn_window: int = 0
    # Rolling (ring-buffer) KV cache for WINDOWED decode: > 0 allocates
    # that many cache rows per slot instead of max_len, with writes
    # wrapping modulo the capacity — serving/generation memory is
    # O(capacity) however long the stream runs. Requires attn_window > 0
    # (a full-causal query needs the whole history) and capacity >=
    # attn_window. Per-token read COST is O(capacity), not O(window)
    # (the ring read is dense over all capacity rows) — size capacity
    # near the window; init_kv_cache warns at >= 4x. Greedy/sampled
    # generate + continuous batching; speculative decoding, beam
    # search, and shared-prefix templates keep the linear cache
    # (models/decode.py rejects the combos).
    kv_cache_capacity: int = 0
    # GPipe microbatch count when the mesh has a pp axis > 1 (forward routes
    # through parallel/pipeline.py automatically). 0 = auto: 2·pp if it
    # divides the batch (bubble (pp-1)/(pp+1)), else pp. Must divide the
    # global batch; the per-microbatch batch must divide the dp axis.
    pp_microbatches: int = 0
    # Pipeline schedule: "gpipe" (differentiable through lm_loss — the
    # default) or "1f1b" (O(pp) instead of O(M) live microbatch
    # activations; gradients come from lm_value_and_grad, not jax.grad —
    # make_train_step's value_and_grad_fn hook).
    pp_schedule: str = "gpipe"
    # MoE: 0 experts = dense MLP
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # KV-cache storage for the decode/serving path: "model" stores K/V in
    # ``dtype`` (exact); "int8" quantizes each written token per kv-head
    # (absmax/127 scale carried in a parallel [.., KV, 1] f32 buffer) —
    # the cache's HBM footprint and read traffic halve vs bf16, so a
    # serving host fits ~2x the slots (or 2x max_len) in the same memory.
    # Decode logits shift by the ~0.4% relative rounding of K/V; training
    # and prefill math are untouched (quantization happens only at the
    # cache write). See models/decode.py.
    kv_cache_dtype: str = "model"

    def __post_init__(self):
        # fail where the config was written, not at first trace
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        if kv <= 0 or self.n_heads % kv:
            raise ValueError(f"n_kv_heads={kv} must be a positive divisor "
                             f"of n_heads={self.n_heads}")
        if self.remat_policy not in ("full", "dots", "attn"):
            raise ValueError(f"unknown remat_policy "
                             f"{self.remat_policy!r}; expected 'full', "
                             f"'dots', or 'attn'")
        if self.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pp_schedule {self.pp_schedule!r}; "
                             f"expected 'gpipe' or '1f1b'")
        if self.kv_cache_dtype not in ("model", "int8"):
            raise ValueError(f"unknown kv_cache_dtype "
                             f"{self.kv_cache_dtype!r}; expected 'model' "
                             f"or 'int8'")
        if self.attn_window < 0:
            raise ValueError(f"attn_window must be >= 0 (0 = full causal "
                             f"attention), got {self.attn_window}")
        if self.kv_cache_capacity:
            if not self.attn_window:
                raise ValueError(
                    "kv_cache_capacity (rolling KV cache) requires "
                    "attn_window > 0: a full-causal query attends the "
                    "whole history, which a ring buffer has overwritten")
            if self.kv_cache_capacity < self.attn_window:
                raise ValueError(
                    f"kv_cache_capacity ({self.kv_cache_capacity}) must "
                    f"be >= attn_window ({self.attn_window}): a decode "
                    f"step reads its window's rows from the ring")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return (self.n_kv_heads if self.n_kv_heads is not None
                else self.n_heads)

    @property
    def kv_quant(self) -> bool:
        return self.kv_cache_dtype == "int8"

    @property
    def logits_storage_dtype(self):
        if self.logits_dtype is not None:
            return self.logits_dtype
        return jnp.bfloat16 if self.dtype == jnp.bfloat16 else jnp.float32

    def scaled(self, **overrides) -> "TransformerConfig":
        return dataclasses.replace(self, **overrides)


# Preset sizes (BASELINE.json progression: ... → BERT-base scale → beyond)
PRESETS = {
    "tiny": TransformerConfig(d_model=128, n_layers=2, n_heads=4, d_ff=512,
                              vocab_size=1024, max_seq=256),
    "small": TransformerConfig(d_model=512, n_layers=8, n_heads=8, d_ff=2048),
    "base": TransformerConfig(d_model=768, n_layers=12, n_heads=12,
                              d_ff=3072),
    "large": TransformerConfig(d_model=1536, n_layers=24, n_heads=16,
                               d_ff=6144),
}


def init_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Initialize the parameter pytree. Layer params are stacked [L, ...]."""
    k_emb, k_blocks, k_out = jax.random.split(rng, 3)
    d, h, hd, f, L = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                      cfg.n_layers)
    dt = cfg.dtype

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    ks = jax.random.split(k_blocks, 8)
    kv = cfg.kv_heads
    block = {
        "attn_norm": jnp.ones((L, d), dt),
        "wq": dense(ks[0], (L, d, h, hd), d),
        "wk": dense(ks[1], (L, d, kv, hd), d),
        "wv": dense(ks[2], (L, d, kv, hd), d),
        "wo": dense(ks[3], (L, h, hd, d), d),
        "mlp_norm": jnp.ones((L, d), dt),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        # experts are 2-matrix MLPs (silu): dispatch/combine already cost
        # two extra einsums, so the gshard path skips the gated "up" branch
        block.update({
            "router": dense(ks[4], (L, d, e), d).astype(jnp.float32),
            "w_gate": dense(ks[5], (L, e, d, f), d),
            "w_down": dense(ks[7], (L, e, f, d), f),
        })
    else:
        block.update({
            "w_gate": dense(ks[5], (L, d, f), d),
            "w_up": dense(ks[6], (L, d, f), d),
            "w_down": dense(ks[7], (L, f, d), f),
        })
    return {
        "embed": (jax.random.normal(k_emb, (cfg.vocab_size, d), jnp.float32)
                  * (d ** -0.5)).astype(dt),
        "blocks": block,
        "final_norm": jnp.ones((d,), dt),
        "lm_head": dense(k_out, (d, cfg.vocab_size), d),
    }


def logical_axes(cfg: TransformerConfig) -> dict:
    """Logical-axis pytree matching init_params (leading axis = "stage" so
    the same layout drives FSDP sharding and pipeline stage assignment)."""
    # Under GQA the K/V head count can be smaller than any tp axis, so
    # those params replicate instead of claiming the "heads" rule (they are
    # n_heads/n_kv_heads× smaller than MHA's to begin with; Llama-style TP
    # replicates KV heads the same way).
    kv_head_axis = "heads" if cfg.kv_heads == cfg.n_heads else None
    block = {
        "attn_norm": ("stage", "norm"),
        "wq": ("stage", "embed", "heads", "kv"),
        "wk": ("stage", "embed", kv_head_axis, "kv"),
        "wv": ("stage", "embed", kv_head_axis, "kv"),
        "wo": ("stage", "heads", "kv", "embed"),
        "mlp_norm": ("stage", "norm"),
    }
    if cfg.num_experts:
        block.update({
            "router": ("stage", "embed", None),
            "w_gate": ("stage", "expert", "embed", "mlp"),
            "w_down": ("stage", "expert", "mlp", "embed"),
        })
    else:
        block.update({
            "w_gate": ("stage", "embed", "mlp"),
            "w_up": ("stage", "embed", "mlp"),
            "w_down": ("stage", "mlp", "embed"),
        })
    return {
        "embed": ("vocab", "embed"),
        "blocks": block,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def rope_tables(positions: jax.Array, d: int) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables [B, S, 1, d/2] for head dim ``d``. Position-only,
    so callers hoist them OUT of the layer scan — recomputing the trig per
    layer cost ~2.3 ms/step at 8 layers × 16×1024 on one v5e."""
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (jnp.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs   # [B, S, half]
    return jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, S, H, D] by precomputed tables (f32 math, x-dtype out)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotary embeddings on [B, S, H, D] (one-shot convenience; the train
    path precomputes the tables once via :func:`rope_tables`)."""
    cos, sin = rope_tables(positions, x.shape[-1])
    return apply_rope(x, cos, sin)


def expand_kv(q: jax.Array, k: jax.Array,
              v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """GQA → full heads: broadcast each K/V head across its query group
    (blocked layout: query head h reads kv head h // (H/KV) — the same
    layout the flash kernels and the grouped cache read use natively).
    Only the context-parallel wrappers need the expansion; the
    flash/reference arms consume GQA K/V unexpanded."""
    h, hk = q.shape[2], k.shape[2]
    if h == hk:
        return k, v
    if hk <= 0 or h % hk:
        raise ValueError(f"kv heads ({hk}) must divide heads ({h})")
    rep = h // hk
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _attention(q, k, v, mesh: Mesh | None, cp_strategy: str = "ring",
               window: int | None = None):
    if cp_strategy not in ("ring", "ulysses"):
        # Silent fallback would make a typo'd strategy benchmark the wrong
        # collective pattern.
        raise ValueError(f"unknown cp_strategy {cp_strategy!r}; "
                         f"expected 'ring' or 'ulysses'")
    if mesh is not None and "cp" in mesh.shape and mesh.shape["cp"] > 1:
        if window is not None:
            # a window crossing chunk boundaries needs window-aware hop
            # masking in the ring (and head-split-aware masking in
            # ulysses) — silently ignoring it would train a different
            # model than the config says
            raise NotImplementedError(
                "attn_window is not supported with context parallelism "
                "(cp > 1) yet; shard long sequences with cp OR bound "
                "attention with a window, not both")
        if cp_strategy == "ulysses":
            # GQA K/V stay unexpanded when kv heads divide tp·cp — the
            # wrapper expands only when the head split cannot be satisfied
            from tony_tpu.parallel.ulysses import ulysses_attention
            return ulysses_attention(q, k, v, mesh, causal=True)
        # ring rides GQA K/V unexpanded: the rotation payload (the ring's
        # whole inter-chip cost) shrinks by n_heads/n_kv_heads
        return ring_attention(q, k, v, mesh, causal=True)
    # flash and reference both consume GQA K/V natively (fewer kv heads)
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, causal=True, window=window)
    return reference_attention(q, k, v, causal=True, window=window)


def _remat_policy(cfg: TransformerConfig):
    """jax.checkpoint policy for cfg.remat_policy (None = save nothing)."""
    if cfg.remat_policy == "full":
        return None
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "attn":
        # save ONLY the flash kernel's outputs (o [B,S,H,D] + lse
        # [B,H,S], named in ops/attention.py's vjp fwd rules): the
        # backward replay recomputes the cheap projections but the
        # O(S²) flash forward is DCE'd — the long-context policy, where
        # remat="full" re-pays the very kernel that dominates the step
        return jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse")
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}; "
                     f"expected 'full', 'dots', or 'attn'")


def _block(x, p, cfg: TransformerConfig, mesh, rules, rope=None,
           ep_axis: str | None = None):
    """One decoder block. x: [B, S, D]; p: this layer's params (unstacked);
    ``rope``: precomputed (cos, sin) tables (derived from positions here
    when absent). ``ep_axis``: set inside shard_map bodies (the pipeline
    stage) to run the MoE arm with explicit ep collectives —
    ``p["w_gate"]/p["w_down"]`` then hold only this rank's experts."""
    b, s, d = x.shape
    if rope is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        rope = rope_tables(positions, cfg.head_dim)
    cos, sin = rope

    h = rms_norm_reference(x, p["attn_norm"])
    h = constrain(h, ("batch", "seq", "embed"), mesh, rules)
    q = jnp.einsum("bsd,dhk->bshk", h, p["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, p["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, p["wv"])
    q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    # GQA K/V stay at kv_heads width — the flash kernels read each shared
    # head once per query group in-kernel (ops/attention.py "GQA-native");
    # only the cp wrappers expand (inside _attention). KV heads replicate
    # under TP when h_kv < h (Llama-style), mirroring logical_axes.
    kv_head_axis = "heads" if cfg.kv_heads == cfg.n_heads else None
    q = constrain(q, ("batch", "seq", "heads", "kv"), mesh, rules)
    k = constrain(k, ("batch", "seq", kv_head_axis, "kv"), mesh, rules)
    v = constrain(v, ("batch", "seq", kv_head_axis, "kv"), mesh, rules)
    o = _attention(q, k, v, mesh, cfg.cp_strategy,
                   cfg.attn_window or None)
    attn_out = jnp.einsum("bshk,hkd->bsd", o, p["wo"])
    x = x + constrain(attn_out, ("batch", "seq", "embed"), mesh, rules)

    h = rms_norm_reference(x, p["mlp_norm"])
    h = constrain(h, ("batch", "seq", "embed"), mesh, rules)
    if "router" in p:
        if ep_axis is not None:
            from tony_tpu.parallel.moe import moe_ffn_manual
            moe_out, metrics = moe_ffn_manual(
                h, p["router"], p["w_gate"], p["w_down"],
                axis_name=ep_axis, num_experts=cfg.num_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                activation=jax.nn.silu)
        else:
            moe_out, metrics = moe_ffn(
                h, p["router"], p["w_gate"], p["w_down"],
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                activation=jax.nn.silu)
        aux = metrics.aux_loss
        mlp_out = moe_out
    else:
        gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"])
        up = jnp.einsum("bsd,df->bsf", h, p["w_up"])
        inner = jax.nn.silu(gate) * up
        inner = constrain(inner, ("batch", "seq", "mlp"), mesh, rules)
        mlp_out = jnp.einsum("bsf,fd->bsd", inner, p["w_down"])
        aux = jnp.zeros((), jnp.float32)
    x = x + constrain(mlp_out, ("batch", "seq", "embed"), mesh, rules)
    return x, aux


def _lm_head(params: dict, x: jax.Array, cfg: TransformerConfig,
             mesh, rules) -> jax.Array:
    """final_norm + lm_head on block output x [B, S, D] → logits."""
    x = rms_norm_reference(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    # The cast fuses into the matmul epilogue, so with bf16 logits_dtype
    # the f32 array never reaches HBM (see TransformerConfig.logits_dtype).
    logits = logits.astype(cfg.logits_storage_dtype)
    return constrain(logits, ("batch", "seq", "vocab"), mesh, rules)


def _pp_layout(cfg: TransformerConfig, mesh: Mesh, batch: int):
    """(pp, microbatches) for a pipelined forward — ONE definition of the
    stage-divisibility check and the auto-microbatch rule, shared by the
    GPipe (:func:`_forward_pp`) and 1F1B (:func:`lm_value_and_grad`)
    arms so the two schedules can never drift apart."""
    pp = mesh.shape.get("pp", 1)
    if cfg.n_layers % max(pp, 1):
        raise ValueError(f"n_layers={cfg.n_layers} not divisible into "
                         f"{pp} pipeline stages")
    m = cfg.pp_microbatches
    if not m:
        # auto: the microbatch dim stays sharded over dp/fsdp inside the
        # pipeline's shard_map, so M must divide b AND leave b/M divisible
        # by the live batch axes — i.e. M | b/dp. Aim for 2·pp (bubble
        # (pp-1)/(3·pp-1)), settle for the largest divisor below it.
        dp_total = 1
        for a in ("dp", "fsdp"):
            dp_total *= mesh.shape.get(a, 1)
        per = max(batch // max(dp_total, 1), 1)
        m = next(k for k in range(min(2 * pp, per), 0, -1) if per % k == 0)
    return pp, m


def _forward_pp(params: dict, tokens: jax.Array, cfg: TransformerConfig,
                mesh: Mesh, rules) -> tuple:
    """Pipeline-parallel forward: blocks run as GPipe stages over the mesh's
    ``pp`` axis (parallel/pipeline.py), embed and lm_head replicated over pp.

    The stacked [L, ...] block layout reshapes to [pp, L/pp, ...] — under the
    "stage"→"pp" sharding rule the leading dim is already split into
    contiguous layer groups per pp rank, so the reshape is shard-local.
    Activations hop stage→stage via ppermute (point-to-point), which is why
    pp is the axis that tolerates DCN (mesh.py AXIS_ORDER).
    """
    from tony_tpu.parallel.pipeline import pipeline_apply

    b, s = tokens.shape
    pp, m = _pp_layout(cfg, mesh, b)
    ep = mesh.shape.get("ep", 1)
    ep_axis = "ep" if (cfg.num_experts and ep > 1) else None
    if ep_axis and cfg.num_experts % ep:
        raise ValueError(f"num_experts={cfg.num_experts} not divisible "
                         f"over ep={ep}")
    x = params["embed"][tokens].astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"), mesh, rules)
    blocks = jax.tree.map(
        lambda a: a.reshape((pp, a.shape[0] // pp) + a.shape[1:]),
        params["blocks"])

    def stage_fn(stage_params, h):
        # runs under shard_map: constrain() inside _block resolves Manual
        # axes to replication (sharding._auto_axes), so the block body is
        # reused verbatim; the MoE arm switches to explicit ep collectives
        # (moe_ffn_manual) because sharding constraints can't reach Manual
        # axes — expert weights arrive pre-sliced via param_specs below
        hb, hs = h.shape[0], h.shape[1]
        positions = jnp.broadcast_to(jnp.arange(hs), (hb, hs))
        rope = rope_tables(positions, cfg.head_dim)
        block_fn = functools.partial(_block, cfg=cfg, mesh=None, rules=rules,
                                     ep_axis=ep_axis)
        if cfg.remat:
            block_fn = jax.checkpoint(block_fn, policy=_remat_policy(cfg))

        def body(carry, p):
            h, acc = carry
            h, aux = block_fn(h, p, rope=rope)
            return (h, acc + aux), None

        (h, aux), _ = jax.lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                                   stage_params, unroll=cfg.scan_unroll)
        return h, aux

    param_specs = None
    if ep_axis:
        # expert-leading leaves ([pp, L/pp, E, ...]) additionally shard E
        # over ep; everything else stays stage-sharded only. The router
        # stays replicated — every rank routes identically.
        from jax.sharding import PartitionSpec as _P
        param_specs = {
            k: (_P("pp", None, "ep") if k in ("w_gate", "w_down")
                else _P("pp"))
            for k in blocks
        }
    x, aux = pipeline_apply(stage_fn, blocks, x, mesh, num_microbatches=m,
                            with_aux=True, param_specs=param_specs)
    logits = _lm_head(params, x, cfg, mesh, rules)
    return logits, aux


def forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
            mesh: Mesh | None = None, rules=DEFAULT_RULES) -> tuple:
    """tokens [B, S] int32 → (logits [B, S, V] in
    cfg.logits_storage_dtype — f32 accumulation, storage-rounded once;
    see TransformerConfig.logits_dtype — and the aux_loss scalar).

    With a mesh whose ``pp`` axis is >1 the blocks run as a GPipe pipeline
    (:func:`_forward_pp`) — pipelining is a mesh change, not a model change.
    """
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        return _forward_pp(params, tokens, cfg, mesh, rules)
    x = params["embed"][tokens].astype(cfg.dtype)
    x = constrain(x, ("batch", "seq", "embed"), mesh, rules)
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    rope = rope_tables(positions, cfg.head_dim)   # hoisted out of the scan

    block_fn = functools.partial(_block, cfg=cfg, mesh=mesh, rules=rules)
    if cfg.remat:
        # rope tables ride the non-differentiated argument slot; marking
        # them static would re-run the trig in every layer's rematerialized
        # forward, which is exactly what hoisting avoids
        block_fn = jax.checkpoint(block_fn, policy=_remat_policy(cfg))

    def scan_body(x, layer_params):
        x, aux = block_fn(x, layer_params, rope=rope)
        return x, aux

    x, auxes = jax.lax.scan(scan_body, x, params["blocks"],
                            unroll=cfg.scan_unroll)
    return _lm_head(params, x, cfg, mesh, rules), auxes.sum()


def train_flops_per_token(cfg: TransformerConfig, seq: int) -> float:
    """Model FLOPs per trained token: forward matmul FLOPs × 3 (backward ≈ 2×
    forward for matmul-dominated graphs). Counts *model* FLOPs only — remat
    recompute is excluded, so this yields MFU (not HFU) when divided by
    wall-clock achieved FLOPs. Attention is counted causal (half of the full
    S² score/value matmuls), matching what the flash kernel actually executes.
    Sliding-window models (cfg.attn_window) count only the attended
    length — the mean over positions of min(position+1, window) — so MFU
    stays an honest achieved/model-FLOPs ratio rather than crediting
    skipped blocks.
    """
    d, f, L, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    kv_width = cfg.kv_heads * cfg.head_dim     # == d for MHA
    proj = 4 * d * d + 4 * d * kv_width   # wq + wo, + wk + wv (GQA-aware)
    if cfg.attn_window and cfg.attn_window < seq:
        w = cfg.attn_window
        # positions 0..w-1 attend position+1 keys; the rest attend w
        mean_attended = (w * (w + 1) / 2 + (seq - w) * w) / seq
        attn = 4 * mean_attended * d      # QK^T + AV over attended keys
    else:
        attn = 2 * seq * d                # QK^T + AV, causal half of 4·S·d
    if cfg.num_experts:
        mlp = 2 * d * cfg.num_experts + cfg.moe_top_k * 4 * d * f
    else:
        mlp = 6 * d * f                   # gate + up + down
    fwd = L * (proj + attn + mlp) + 2 * d * v   # + lm_head
    return 3.0 * fwd


def lm_loss(params: dict, batch: dict, cfg: TransformerConfig,
            mesh: Mesh | None = None, rules=DEFAULT_RULES) -> jax.Array:
    """Next-token cross-entropy. batch: {"tokens": [B, S]} (shift inside) or
    {"inputs", "targets"}; ignores targets == -1."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    logits, aux = forward(params, inputs, cfg, mesh, rules)
    return masked_cross_entropy(logits, targets) + cfg.moe_aux_weight * aux


def lm_value_and_grad(params: dict, batch: dict, cfg: TransformerConfig,
                      mesh: Mesh, rules=DEFAULT_RULES):
    """Next-token loss AND parameter gradients via the 1F1B pipeline
    schedule (``cfg.pp_schedule == "1f1b"``) — the memory-scalable arm of
    pipeline parallelism (parallel/pipeline.py: O(pp) live microbatch
    activations instead of GPipe's O(M)).

    Not a ``jax.grad`` target: 1F1B starts each microbatch's backward at
    the last stage as soon as its forward lands, which requires the loss
    head inside the pipeline — so this function IS the differentiation.
    Plug into ``make_train_step(..., value_and_grad_fn=...)``.

    The loss normalizes per (microbatch, data shard) — identical to
    :func:`lm_loss` whenever mask counts are uniform (always true for
    dense LM batches without -1 padding).
    """
    from tony_tpu.models.train import masked_cross_entropy as _mxe
    from tony_tpu.parallel.pipeline import pipeline_value_and_grad

    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    if cfg.num_experts and mesh.shape.get("ep", 1) > 1:
        raise NotImplementedError(
            "pp_schedule='1f1b' supports MoE with REPLICATED experts "
            "only (no ep axis): the explicit-collective dispatch's psum "
            "transposes are not exact under the schedule's per-rank "
            "vjps — shard experts over ep with pp_schedule='gpipe'")
    b, s = inputs.shape
    pp, m = _pp_layout(cfg, mesh, b)

    def embed_fn(e):
        x = e[inputs].astype(cfg.dtype)
        return constrain(x, ("batch", "seq", "embed"), mesh, rules)

    x, embed_vjp = jax.vjp(embed_fn, params["embed"])

    with_aux = bool(cfg.num_experts)

    def stage_fn(stage_params, h):
        hb, hs = h.shape[0], h.shape[1]
        positions = jnp.broadcast_to(jnp.arange(hs), (hb, hs))
        rope = rope_tables(positions, cfg.head_dim)
        block_fn = functools.partial(_block, cfg=cfg, mesh=None,
                                     rules=rules)
        if cfg.remat:
            block_fn = jax.checkpoint(block_fn, policy=_remat_policy(cfg))

        def body(carry, p):
            h, acc = carry
            h, aux = block_fn(h, p, rope=rope)
            return (h, acc + aux), None

        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.float32)), stage_params,
            unroll=cfg.scan_unroll)
        return (h, aux) if with_aux else h

    # Loss head: vocab-sharded over tp when the mesh can (matching the
    # GPipe arm, where the lm_head stays tp-sharded by propagation) — the
    # head runs inside the pipeline's Manual context, so the softmax
    # combines across vocab shards with explicit collectives (distributed
    # logsumexp + psum-picked logit); the pipeline psums the activation
    # cotangent across tp (pipeline_value_and_grad head_reduce_axes).
    tp = mesh.shape.get("tp", 1)
    vocab_sharded = (tp > 1 and cfg.vocab_size % tp == 0
                     and mesh.shape.get("pp", 1) > 1)
    if vocab_sharded:
        from jax.sharding import PartitionSpec as _P

        def loss_head(hp, out_mb, tgt_mb):
            h = rms_norm_reference(out_mb, hp["final_norm"])
            logits = jnp.einsum("bsd,dv->bsv", h, hp["lm_head"],
                                preferred_element_type=jnp.float32)
            # same storage rounding as _lm_head, math back in f32
            logits = logits.astype(cfg.logits_storage_dtype).astype(
                jnp.float32)
            v_loc = logits.shape[-1]
            shard = jax.lax.axis_index("tp")
            # the max is a numerical stabilizer only — lse is shift-
            # invariant and the shift's gradient cancels exactly, so stop
            # gradients rather than differentiate pmax (which has no rule)
            gmax = jax.lax.pmax(
                jax.lax.stop_gradient(jnp.max(logits, axis=-1)), "tp")
            # psum_rep: identity transpose so per-rank vjps yield TRUE
            # partials (the stock psum transpose re-psums a replicated
            # cotangent, scaling every upstream gradient by tp); the
            # pipeline sums the partials across tp exactly once
            # (head_reduce_axes)
            from tony_tpu.parallel.sharding import psum_rep
            lse = gmax + jnp.log(psum_rep(
                jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1),
                "tp"))
            ids = shard * v_loc + jnp.arange(v_loc)
            onehot = tgt_mb[..., None] == ids
            picked = psum_rep(
                jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1), "tp")
            mask = (tgt_mb >= 0).astype(jnp.float32)
            return ((lse - picked) * mask).sum() / jnp.maximum(
                mask.sum(), 1.0)

        head_specs = {"final_norm": _P(), "lm_head": _P(None, "tp")}
        reduce_axes = ("tp",)
    else:
        def loss_head(hp, out_mb, tgt_mb):
            logits = _lm_head(hp, out_mb, cfg, None, rules)
            return _mxe(logits, tgt_mb)

        head_specs, reduce_axes = None, ()

    head_params = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}
    blocks = jax.tree.map(
        lambda a: a.reshape((pp, a.shape[0] // pp) + a.shape[1:]),
        params["blocks"])
    loss, g_blocks, g_head, dx = pipeline_value_and_grad(
        stage_fn, blocks, x, head_params, targets, mesh,
        loss_head=loss_head, num_microbatches=m,
        head_specs=head_specs, head_reduce_axes=reduce_axes,
        with_aux=with_aux, aux_weight=cfg.moe_aux_weight)
    (g_embed,) = embed_vjp(dx)
    grads = {
        "embed": g_embed,
        "blocks": jax.tree.map(
            lambda g: g.reshape((g.shape[0] * g.shape[1],) + g.shape[2:]),
            g_blocks),
        "final_norm": g_head["final_norm"],
        "lm_head": g_head["lm_head"],
    }
    return loss, grads
