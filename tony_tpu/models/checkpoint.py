"""Checkpoint/resume for train state, retry-aware.

The reference delegates checkpointing entirely to the user script
(reference: tony-examples/mnist-tensorflow/mnist_distributed.py:223-227 uses
``MonitoredTrainingSession(checkpoint_dir=...)``; SURVEY.md §5 "checkpoint /
resume: DELEGATED") and restarts training on AM retry from whatever the
script restores, exposing ``ATTEMPT_NUMBER`` so scripts can detect retries
(reference: TonyApplicationMaster.java:593). The TPU build keeps that
division of labor but ships the recipe: an orbax-backed manager that is
sharding-aware (restores arrays onto the same device mesh layout they were
saved from — essential when a preempted slice job resumes) and a
``restore_or_init`` helper that makes user scripts retry-safe in one line:

    mgr = CheckpointManager(ckpt_dir)
    state = mgr.restore_or_init(lambda: init_state(params, opt))
    for step in range(mgr.latest_step() or 0, total_steps):
        state, metrics = train_step(state, batch)
        mgr.save(step, state)
    mgr.close()

Multi-host: orbax coordinates distributed save/restore across jax processes
itself; every process must call save/restore collectively.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable

import jax

log = logging.getLogger(__name__)


def attempt_number() -> int:
    """Which coordinator attempt this process belongs to (0 = first run).
    Reference exports the same env var for the same purpose
    (TonyApplicationMaster.java:593, Constants.java ATTEMPT_NUMBER)."""
    from tony_tpu import constants
    return int(os.environ.get(constants.ATTEMPT_NUMBER, "0"))


class CheckpointManager:
    """Thin, typed wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1) -> None:
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True))

    # ------------------------------------------------------------------
    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save if the step hits the save interval; returns True if saved."""
        saved = self._mgr.save(
            int(step), args=self._ocp.args.StandardSave(state), force=force)
        if saved:
            log.info("checkpoint saved at step %d → %s", step, self.directory)
        return saved

    def _fence_in_flight_saves(self) -> None:
        """Read barrier for the async save pipeline: ``save`` returns
        before the checkpoint is committed, so ``latest_step``/``restore``
        must never run concurrently with this process's own in-flight
        write — an elastic resume that restores right after offering a
        save would otherwise race the commit (orbax keeps uncommitted
        steps out of ``latest_step`` via tmp-dir + atomic finalize, but
        only the fence makes "restore sees every save this process
        already accepted" a guarantee rather than a filesystem property).
        Never raises into the read path: a failed async save surfaces on
        the next save/close, not as a broken restore of older steps."""
        try:
            self._mgr.wait_until_finished()
        except Exception:
            log.warning("in-flight checkpoint save failed; restoring from "
                        "the latest COMMITTED step", exc_info=True)

    def latest_step(self) -> int | None:
        self._fence_in_flight_saves()
        return self._mgr.latest_step()

    def restore(self, template: Any | None = None,
                step: int | None = None) -> Any:
        """Restore the given (or latest) step. ``template`` is a matching
        pytree (abstract or concrete) guiding sharding/dtype placement —
        pass the freshly-initialized state so arrays land on the same mesh
        layout they were saved from. Fenced against in-flight async
        saves: a partially-written checkpoint is never observed."""
        self._fence_in_flight_saves()
        step = self._mgr.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if template is None:
            args = self._ocp.args.StandardRestore()
        else:
            abstract = jax.tree.map(_abstractify, template)
            args = self._ocp.args.StandardRestore(abstract)
        return self._mgr.restore(step, args=args)

    def restore_or_init(self, init_fn: Callable[[], Any]) -> Any:
        """The retry-safe bootstrap: restore the latest checkpoint if one
        exists, else build fresh state. On coordinator retries
        (ATTEMPT_NUMBER > 0) a missing checkpoint is still fine — the job
        may have died before the first save."""
        state = init_fn()
        step = self.latest_step()       # fenced against in-flight saves
        if step is None:
            if attempt_number() > 0:
                log.warning(
                    "attempt %d but no checkpoint in %s — starting fresh",
                    attempt_number(), self.directory)
            return state
        log.info("resuming from checkpoint step %d (attempt %d)",
                 step, attempt_number())
        return self.restore(template=state, step=step)

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _abstractify(x):
    """Concrete array → ShapeDtypeStruct carrying its sharding."""
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x
