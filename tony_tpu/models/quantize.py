"""Weight-only int8 quantization for the serving path.

Small-batch decode is PARAMETER-READ-bound: every generated token streams
the full weight set from HBM (the lm_head read alone is ~40 µs/step at
the small preset — docs/performance.md "Decode"), so halving the bytes
per weight is a direct decode-latency lever, independent of the int8
KV-cache work (`decode.init_kv_cache`) that halves the *cache* traffic.
Green-field for the TPU build (SURVEY.md §2.3 — the reference delegates
all compute and has no serving path).

Scheme: symmetric per-OUTPUT-CHANNEL absmax int8. For every served
matmul ``y = x @ W`` the scale is constant along the contracted axes, so
it factors OUT of the dot: the kernels compute
``(x @ W_int8.astype(f32)) * scale`` — f32, NOT bf16: XLA fuses the
int8→f32 convert into the dot's operand read while int8→bf16
MATERIALIZES a full-size converted copy (measured 3× slower on the
lm_head matmul; integers ≤ 127 are exact either way). The HBM weight
read stays int8-wide; `decode._weinsum` is the single dispatch point.
Same fold as the int8 KV cache's score/value scales.

PREFILL-shaped matmuls invert the trade: over a long prompt the dot is
COMPUTE-bound, f32 MXU throughput sits far below bf16, and the
materialized bf16 weight copy amortizes across the many activation
rows — so `_weinsum` converts the int8 weight to the bf16 compute
dtype once per call when the activation's SEQUENCE axis exceeds 256
positions (decode-shaped calls at any batch size and f32 activations
keep the fused-f32 path bit-for-bit; the strict on-a-power-of-two
threshold keeps bucket-padded admission and exact-length prefills of
the same prompt on the same kernel — see `_QUANT_PREFILL_MIN_S`). The
scale still applies outside the contraction in f32. `bench.py`'s
`prefill_wq8_vs_bf16` arm pins quantized prefill near float-weight
prefill; without this, serving int8 weights paid a time-to-first-token
tax exactly where admission cost matters (ADVICE.md round 5).

Scope: the decode/serving entry points (`decode.prefill`,
``extend_step``/``decode_step`` and everything built on them — generate,
beam search, speculative decoding, continuous batching) consume
quantized params transparently; the TRAINING forward does not (training
needs weight gradients — quantize a snapshot for serving, keep training
params full-precision). MoE expert weights and the embedding gather stay
full-precision (the router's capacity math and the gather don't go
through the matmul dispatcher); norms are vectors, not worth rounding.

Numerics: per-channel absmax keeps the relative rounding ≤ 1/254 per
weight; decoded logits shift ~0.5-1% relative vs the float weights, so
greedy decode is no longer bit-identical to the float model (near-tie
argmaxes can flip) — but all quant-to-quant equivalences (serving ==
generate, beam W=1 == greedy, speculative == greedy) hold and are
test-enforced, the same contract as the int8 KV cache.

Usage::

    sparams = quantize_weights_int8(params)     # serving snapshot
    out = decode.generate(sparams, prompt, cfg, n, rng)

Tensor-parallel serving: quantize AFTER sharding, not before —
``shard_pytree`` resolves logical axes against plain array leaves, so
quantize the already-placed float params and the int8 weights/scales
inherit the weights' shardings through the elementwise/reduction ops
(q keeps the weight's spec; the per-output-channel scale keeps the
output axes' spec). TP-sharded quantized decode is token-identical to
unsharded quantized decode (test-verified on the virtual mesh)::

    sharded = shard_pytree(params, T.logical_axes(cfg), mesh)
    sparams = quantize_weights_int8(sharded)
    with jax.set_mesh(mesh):
        out = decode.generate(sparams, prompt, cfg, n, rng)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedWeight(NamedTuple):
    """int8 weight + per-output-channel f32 scale. A pytree (NamedTuple),
    so it flows through jit/tree.map like the array it replaces: layer
    indexing ``tree.map(lambda a: a[li], blocks)`` slices ``q`` and
    ``scale`` together (both keep the stacked [L, ...] leading dim)."""
    q: jax.Array        # int8, the original weight's shape
    scale: jax.Array    # f32, the output-channel dims (contracted axes
    #                     squeezed out)


def _quantize(w: jax.Array, contract_axes: tuple[int, ...]
              ) -> QuantizedWeight:
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=contract_axes, keepdims=True)
    scale = jnp.maximum(scale / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedWeight(q, jnp.squeeze(scale, contract_axes))


#: blocks-pytree weights served through matmuls, with the axes each
#: contracts over (leading dim 0 is the stacked layer axis L)
_BLOCK_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,),    # [L, d, H, hd] contract d
    "wo": (1, 2),                          # [L, H, hd, d] contract (H, hd)
    "w_gate": (1,), "w_up": (1,),          # [L, d, f]     contract d
    "w_down": (1,),                        # [L, f, d]     contract f
}


def quantize_weights_int8(params: dict) -> dict:
    """Serving snapshot: the dense matmul weights → :class:`QuantizedWeight`
    (per-output-channel int8); everything else (embed, norms, MoE
    experts/router) passes through unchanged. The returned pytree is a
    drop-in ``params`` for every ``tony_tpu.models.decode`` entry point."""
    blocks = dict(params["blocks"])
    moe = "router" in blocks
    for name, axes in _BLOCK_AXES.items():
        if name not in blocks:
            continue
        if moe and name in ("w_gate", "w_up", "w_down"):
            continue        # expert weights ride moe_ffn's own dispatch
        blocks[name] = _quantize(blocks[name], axes)
    out = dict(params, blocks=blocks)
    out["lm_head"] = _quantize(params["lm_head"], (0,))   # [d, v]
    return out
