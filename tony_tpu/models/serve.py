"""Continuous batching for the serving path.

Static-batch serving (one :func:`~tony_tpu.models.decode.generate` call
per request batch) leaves rows idle from the moment they finish until the
LAST row finishes — at mixed request lengths most of the batch is dead
weight. Continuous batching retires a row the step it completes and
admits the next queued request into its cache slot while the other rows
keep decoding; utilization follows the OFFERED load, not the slowest
request. (The industry-standard serving pattern; green-field here —
SURVEY.md §2.3, the reference delegates all compute and has no serving
path.)

The round-5 per-row decode machinery is exactly what makes this cheap
(models/decode.py): cache ``length`` is a [B] vector, RoPE positions,
causal masks, and K/V writes all take per-row frontiers, and the
length-aware block-wise attention reads only each batch's LIVE rows of a
shared padded cache. On top of that, three small device programs:

- :func:`admit_row` — a batch-1 prefill whose K/V land in the retired
  row's cache slot (one contiguous ``dynamic_update_slice`` per buffer)
  and whose last-position logits seed the row's next step;
- :func:`step_rows` — a ``lax.scan`` of ``n`` per-row greedy decode
  steps over the whole batch (one dispatch per chunk, not per token);
- :func:`retire_rows` — zero the freed rows' frontiers so idle slots
  never walk off the end of the cache.

Correctness argument for slot reuse: a row's queries attend positions
``<= pos_r`` only. A new occupant's prefill rewrites positions
``[0, S_prompt)`` and its decode steps write exactly at ``pos_r`` before
reading it, so every position a query can reach was written by the
CURRENT occupant — the previous request's stale K/V beyond the frontier
is unreachable by construction (the same argument the speculative
decoder makes for rejected-draft entries).

The admission loop itself (:class:`ContinuousBatcher`) is host-driven —
admission is inherently data-dependent control flow (which request, into
which slot, at what length) and runs at human/request rate, while the
token loop stays on device in ``step_rows`` chunks.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp

from tony_tpu.models import transformer as T
from tony_tpu.models.decode import decode_step, init_kv_cache, prefill


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache", "logits"))
def admit_row(params, cache, logits, row, prompt, cfg):
    """Admit a request into cache slot ``row``.

    prompt: [1, S_p] (batch-1 prefill; retraces per distinct prompt
    length — pad/bucket lengths upstream if that matters). Returns
    (cache, logits) with the row's K/V filled, its frontier at S_p, and
    its next-step logits seeded.
    """
    lg1, mini = prefill(params, prompt, cfg, max_len=prompt.shape[1])
    new_k = jax.lax.dynamic_update_slice(cache["k"], mini["k"],
                                         (0, row, 0, 0, 0))
    new_v = jax.lax.dynamic_update_slice(cache["v"], mini["v"],
                                         (0, row, 0, 0, 0))
    length = cache["length"].at[row].set(prompt.shape[1])
    return ({"k": new_k, "v": new_v, "length": length},
            logits.at[row].set(lg1[0]))


@functools.partial(jax.jit, static_argnames=("cfg", "n"),
                   donate_argnames=("cache", "logits"))
def step_rows(params, cache, logits, n, cfg):
    """``n`` greedy decode steps for every row at its OWN frontier.
    Returns (tokens [B, n], cache, logits). Idle rows decode garbage
    that the host discards — uniform batch math keeps this one compiled
    program regardless of which rows are live."""

    def body(carry, _):
        lg, c = carry
        tok = jnp.argmax(lg, axis=-1)
        lg, c = decode_step(params, tok, c, c["length"], cfg)
        return (lg, c), tok

    (lg, cache), toks = jax.lax.scan(body, (logits, cache), None, length=n)
    return toks.T, cache, lg


@functools.partial(jax.jit, donate_argnames=("cache",))
def retire_rows(cache, mask):
    """Reset retired rows' frontiers to 0 (mask: [B] bool). Keeps idle
    slots from marching their garbage frontier into the cache end."""
    return dict(cache, length=jnp.where(mask, 0, cache["length"]))


class ContinuousBatcher:
    """Host-side admission loop over the device programs above.

    ``serve(prompts, max_new_tokens)`` runs every request to completion
    (``max_new_tokens`` or ``eos_id``) through a fixed ``batch`` of cache
    slots, admitting the next queued request the moment a slot frees.
    Outputs are the same greedy tokens :func:`decode.generate` produces
    for each request alone (test-verified token-identical on CPU).
    """

    def __init__(self, params, cfg: T.TransformerConfig, batch: int,
                 max_len: int, eos_id: int | None = None,
                 chunk: int = 8) -> None:
        self.params = params
        self.cfg = cfg
        self.batch = batch
        self.max_len = max_len
        self.eos_id = eos_id
        #: device steps per host round trip — latency/overhead trade:
        #: a finished row idles at most chunk-1 steps before its slot
        #: is reused
        self.chunk = max(1, chunk)
        self.cache = init_kv_cache(cfg, batch, max_len)
        # per-row frontiers from the start (decode.py's [B] position path)
        self.cache = dict(self.cache,
                          length=jnp.zeros((batch,), jnp.int32))
        self.logits = jnp.zeros((batch, cfg.vocab_size),
                                cfg.logits_storage_dtype)

    def serve(self, prompts: Sequence, max_new_tokens):
        """Run all ``prompts`` (each a [S_p] int sequence) to completion;
        returns a list of per-request generated-token lists, order-
        matching the input. ``max_new_tokens``: one int for all requests
        or a per-request sequence (mixed-length serving is the whole
        point). ``self.steps_executed`` counts device decode steps run —
        the utilization denominator (each step advances every slot)."""
        import numpy as np

        queue = list(range(len(prompts)))
        outputs: list[list[int]] = [[] for _ in prompts]
        if isinstance(max_new_tokens, int):
            budget = [max_new_tokens] * len(prompts)
        else:
            budget = list(max_new_tokens)
            if len(budget) != len(prompts):
                raise ValueError("per-request max_new_tokens length "
                                 "must match prompts")
        # validate EVERY request before admitting any: a mid-serve raise
        # would discard completed outputs and strand the batcher state
        for req, (p, b) in enumerate(zip(prompts, budget)):
            if len(p) == 0:
                raise ValueError(f"request {req}: empty prompt")
            if b <= 0:
                raise ValueError(f"request {req}: max_new_tokens must be "
                                 f"positive, got {b}")
            if len(p) + b > self.max_len:
                raise ValueError(
                    f"request {req}: prompt {len(p)} + {b} new tokens "
                    f"exceeds max_len {self.max_len}")
        occupant: list[int | None] = [None] * self.batch
        self.steps_executed = 0

        def admit_next(row: int) -> None:
            req = queue.pop(0)
            tok = jnp.asarray(prompts[req], jnp.int32)[None]
            self.cache, self.logits = admit_row(
                self.params, self.cache, self.logits, row, tok, self.cfg)
            occupant[row] = req

        for row in range(self.batch):
            if queue:
                admit_next(row)

        while any(o is not None for o in occupant):
            toks, self.cache, self.logits = step_rows(
                self.params, self.cache, self.logits, self.chunk, self.cfg)
            self.steps_executed += self.chunk
            host_toks = np.asarray(toks)
            freed = []
            for row, req in enumerate(occupant):
                if req is None:
                    continue
                for t in host_toks[row]:
                    outputs[req].append(int(t))
                    budget[req] -= 1
                    if budget[req] == 0 or (self.eos_id is not None
                                            and int(t) == self.eos_id):
                        # surplus chunk tokens past completion discarded
                        occupant[row] = None
                        freed.append(row)
                        break
            for row in freed:
                if queue:
                    admit_next(row)
            # reset ALL unoccupied rows (not just newly freed): a slot
            # idle across many chunks would otherwise march its garbage
            # frontier every step until it clamps at the cache end
            if any(o is None for o in occupant):
                self.cache = retire_rows(
                    self.cache,
                    jnp.asarray([o is None for o in occupant]))
        return outputs
